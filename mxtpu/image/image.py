"""Image decode, transforms, augmenters, and ImageIter.

Parity: python/mxnet/image/image.py (imdecode, resize_short, fixed_crop,
random_crop, center_crop, color_normalize, the *Aug classes,
CreateAugmenter :719, ImageIter :975). Implemented over cv2 (same backend
as the reference's OpenCV path) with numpy; outputs are mxtpu NDArrays in
HWC until the final NCHW batch assembly, matching the reference layout
contract.
"""
from __future__ import annotations

import os
import random as _pyrandom

import numpy as _np

from ..base import MXNetError
from .. import io as _io
from .. import ndarray as nd
from ..ndarray import NDArray

try:
    import cv2 as _cv2
except ImportError:  # pragma: no cover - cv2 is baked in normally
    _cv2 = None

__all__ = [
    "imdecode", "imread", "imresize", "copyMakeBorder", "scale_down",
    "resize_short", "fixed_crop", "random_crop", "center_crop",
    "color_normalize", "random_size_crop", "Augmenter", "ResizeAug",
    "ForceResizeAug", "RandomCropAug", "RandomSizedCropAug",
    "CenterCropAug", "RandomOrderAug", "BrightnessJitterAug",
    "ContrastJitterAug", "SaturationJitterAug", "ColorJitterAug",
    "LightingAug", "ColorNormalizeAug", "HorizontalFlipAug", "CastAug",
    "CreateAugmenter", "ImageIter",
]


def _as_np(img):
    if isinstance(img, NDArray):
        return img.asnumpy()
    return _np.asarray(img)


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an encoded image buffer to HWC uint8 (parity op _cvimdecode /
    image.py imdecode). flag: 1 color, 0 grayscale."""
    if _cv2 is None:
        raise MXNetError("imdecode requires cv2")
    raw = _np.frombuffer(bytes(buf), dtype=_np.uint8)
    img = _cv2.imdecode(raw, 1 if flag else 0)
    if img is None:
        raise MXNetError("imdecode: cannot decode buffer")
    if flag and to_rgb:
        img = _cv2.cvtColor(img, _cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[:, :, None]
    arr = nd.array(img.astype(_np.uint8), dtype="uint8")
    if out is not None:
        out._data = arr._data
        return out
    return arr


def imread(filename, flag=1, to_rgb=True):
    """Read+decode an image file (parity op _cvimread)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imdecode_np(buf, flag=1, to_rgb=True):
    """Decode straight to a numpy HWC uint8 array (no NDArray hop) — the
    hot path of ImageRecordIter's threaded decode."""
    if _cv2 is None:
        raise MXNetError("imdecode requires cv2")
    raw = _np.frombuffer(bytes(buf), dtype=_np.uint8)
    img = _cv2.imdecode(raw, 1 if flag else 0)
    if img is None:
        raise MXNetError("imdecode: cannot decode buffer")
    if flag and to_rgb:
        img = _cv2.cvtColor(img, _cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def imresize_np(src, w, h, interp=1):
    """numpy->numpy resize (no NDArray hop)."""
    if _cv2 is None:
        raise MXNetError("imresize requires cv2")
    return _cv2.resize(src, (int(w), int(h)), interpolation=int(interp))


def imresize(src, w, h, interp=1):
    """Resize to exactly (w, h) (parity op _cvimresize)."""
    if _cv2 is None:
        raise MXNetError("imresize requires cv2")
    img = _as_np(src)
    out = _cv2.resize(img, (int(w), int(h)), interpolation=int(interp))
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out, dtype=str(img.dtype))


def copyMakeBorder(src, top, bot, left, right, border_type=0, value=0.0):
    """Pad an image (parity op _cvcopyMakeBorder)."""
    if _cv2 is None:
        raise MXNetError("copyMakeBorder requires cv2")
    img = _as_np(src)
    out = _cv2.copyMakeBorder(img, top, bot, left, right, border_type,
                              value=value)
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out, dtype=str(img.dtype))


def scale_down(src_size, size):
    """Scale (w, h) down to fit src_size keeping aspect (parity image.py)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge equals size (parity image.py:290)."""
    img = _as_np(src)
    h, w = img.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(img, new_w, new_h, interp=interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    img = _as_np(src)
    out = img[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(out, size[0], size[1], interp=interp)
    return nd.array(out, dtype=str(img.dtype))


def random_crop(src, size, interp=2):
    img = _as_np(src)
    h, w = img.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(img, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    img = _as_np(src)
    h, w = img.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(img, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    img = _as_np(src).astype(_np.float32)
    mean = _as_np(mean) if mean is not None else None
    if mean is not None:
        img = img - mean
    if std is not None:
        img = img / _as_np(std)
    return nd.array(img)


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Random area+aspect crop (parity image.py random_size_crop)."""
    img = _as_np(src)
    h, w = img.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = _pyrandom.uniform(min_area, 1.0) * area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        aspect = _np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * aspect) ** 0.5))
        new_h = int(round((target_area / aspect) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(img, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(img, size, interp)


class Augmenter:
    """Base augmenter (parity image.py Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [resize_short(src, self.size, self.interp)]


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [imresize(src, self.size[0], self.size[1], self.interp)]


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [random_crop(src, self.size, self.interp)[0]]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, min_area, ratio, interp=2):
        super().__init__(size=size, min_area=min_area, ratio=ratio,
                         interp=interp)
        self.size = size
        self.min_area = min_area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return [random_size_crop(src, self.size, self.min_area, self.ratio,
                                 self.interp)[0]]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [center_crop(src, self.size, self.interp)[0]]


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        srcs = [src]
        ts = list(self.ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            srcs = [out for s in srcs for out in t(s)]
        return srcs


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return [nd.array(_as_np(src).astype(_np.float32) * alpha)]


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        img = _as_np(src).astype(_np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        gray = (img * self._coef).sum() * (3.0 / img.size)
        return [nd.array(img * alpha + gray * (1.0 - alpha))]


class SaturationJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        img = _as_np(src).astype(_np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        gray = (img * self._coef).sum(axis=2, keepdims=True)
        return [nd.array(img * alpha + gray * (1.0 - alpha))]


class HueJitterAug(Augmenter):
    """Random hue shift in YIQ space (parity image.py HueJitterAug)."""

    _u = _np.array([[0.299, 0.587, 0.114],
                    [0.596, -0.274, -0.321],
                    [0.211, -0.523, 0.311]], _np.float32)
    _v = _np.array([[1.0, 0.956, 0.621],
                    [1.0, -0.272, -0.647],
                    [1.0, -1.107, 1.705]], _np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        img = _as_np(src).astype(_np.float32)
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        a = _np.pi * alpha
        rot = _np.array([[1, 0, 0],
                         [0, _np.cos(a), -_np.sin(a)],
                         [0, _np.sin(a), _np.cos(a)]], _np.float32)
        t = self._v.T @ rot @ self._u.T
        return [nd.array(img @ t.astype(_np.float32))]


class RandomGrayAug(Augmenter):
    """Randomly convert to 3-channel grayscale (parity RandomGrayAug)."""

    _coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            img = _as_np(src).astype(_np.float32)
            gray = (img * self._coef).sum(axis=2, keepdims=True)
            return [nd.array(_np.broadcast_to(gray, img.shape).copy())]
        return [src if hasattr(src, "asnumpy") else nd.array(_as_np(src))]


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA lighting noise (parity image.py LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, _np.float32)
        self.eigvec = _np.asarray(eigvec, _np.float32)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = _np.dot(self.eigvec * alpha, self.eigval)
        return [nd.array(_as_np(src).astype(_np.float32) + rgb)]


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = None if mean is None else _np.asarray(mean, _np.float32)
        self.std = None if std is None else _np.asarray(std, _np.float32)

    def __call__(self, src):
        return [color_normalize(src, self.mean, self.std)]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return [nd.array(_as_np(src)[:, ::-1].copy())]
        return [nd.array(_as_np(src))]


class CastAug(Augmenter):
    def __call__(self, src):
        return [nd.array(_as_np(src).astype(_np.float32))]


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Standard augmenter chain (parity image.py CreateAugmenter:719)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.3, (3.0 / 4.0,
                                                           4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = _np.asarray(mean)
        assert mean.shape[0] in [1, 3]
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = _np.asarray(std)
        assert std.shape[0] in [1, 3]
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(_io.DataIter):
    """Pure-Python image iterator over .rec files or image lists
    (parity image.py ImageIter:975).

    Supports path_imgrec (recordio) or path_imglist/imglist + path_root
    (loose image files), shuffle, part reading for distributed loaders,
    and an augmenter chain. Batches come out NCHW RGB.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        assert len(data_shape) == 3 and data_shape[0] in (1, 3)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.seq = None
        self.imgrec = None
        self.imglist = None

        if path_imgrec is not None:
            from .. import recordio as rio
            if path_imgidx is None and os.path.exists(
                    os.path.splitext(path_imgrec)[0] + ".idx"):
                path_imgidx = os.path.splitext(path_imgrec)[0] + ".idx"
            if path_imgidx is not None:
                self.imgrec = rio.MXIndexedRecordIO(path_imgidx, path_imgrec,
                                                    "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = rio.MXRecordIO(path_imgrec, "r")
        elif path_imglist is not None:
            imglist = {}
            seq = []
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    label = _np.array([float(x) for x in parts[1:-1]],
                                      dtype=_np.float32)
                    key = int(parts[0])
                    imglist[key] = (label, parts[-1])
                    seq.append(key)
            self.imglist = imglist
            self.seq = seq
        elif imglist is not None:
            result = {}
            seq = []
            for i, (label, fname) in enumerate(imglist):
                label = _np.array(label, dtype=_np.float32).reshape(-1)
                result[i] = (label, fname)
                seq.append(i)
            self.imglist = result
            self.seq = seq
        else:
            raise MXNetError(
                "ImageIter needs path_imgrec, path_imglist, or imglist")
        self.path_root = path_root
        if self.seq is not None and num_parts > 1:
            part = len(self.seq) // num_parts
            self.seq = self.seq[part * part_index:part * (part_index + 1)]
        self.shuffle = shuffle
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.provide_data = [_io.DataDesc(data_name,
                                          (batch_size,) + self.data_shape)]
        if label_width > 1:
            self.provide_label = [_io.DataDesc(label_name,
                                               (batch_size, label_width))]
        else:
            self.provide_label = [_io.DataDesc(label_name, (batch_size,))]
        self.cur = 0
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            _pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        """(label, decoded HWC image) for the next sample."""
        flag = 1 if self.data_shape[0] == 3 else 0  # grayscale decode for C=1
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                from .. import recordio as rio
                header, img = rio.unpack(s)
                return header.label, imdecode(img, flag=flag)
            label, fname = self.imglist[idx]
            return label, imread(os.path.join(self.path_root or "", fname),
                                 flag=flag)
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        from .. import recordio as rio
        header, img = rio.unpack(s)
        return header.label, imdecode(img, flag=flag)

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = _np.zeros((batch_size, h, w, c), dtype=_np.float32)
        batch_label = _np.zeros((batch_size, self.label_width),
                                dtype=_np.float32)
        i = 0
        try:
            while i < batch_size:
                label, img = self.next_sample()
                arr = _as_np(img)
                for aug in self.auglist:
                    arr = _as_np(aug(arr)[0])
                if arr.shape[:2] != (h, w):
                    raise MXNetError(
                        "ImageIter: augmented image %s != data_shape %s; add "
                        "a resize/crop augmenter" % (arr.shape, (h, w)))
                batch_data[i] = arr.reshape(h, w, c)
                batch_label[i] = _np.asarray(label, _np.float32).reshape(-1)[
                    :self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = batch_size - i
        data = nd.array(batch_data.transpose(0, 3, 1, 2))
        label = nd.array(batch_label[:, 0] if self.label_width == 1
                         else batch_label)
        return _io.DataBatch(data=[data], label=[label], pad=pad, index=None)
