"""Detection image pipeline: box-aware augmenters + ImageDetIter.

Parity: python/mxnet/image/detection.py (DetBorrowAug, DetRandomSelectAug,
DetHorizontalFlipAug, DetRandomCropAug, DetRandomPadAug,
CreateDetAugmenter, ImageDetIter) and the native detection augmenter chain
src/io/image_det_aug_default.cc.

Label convention (same as the reference's .lst/.rec detection format):
per-image label = [header_width, object_width, extra..., obj0, obj1, ...]
where each object is [id, xmin, ymin, xmax, ymax, extra...] with
coordinates normalized to [0, 1]. The iterator reshapes that into a padded
(max_objects, object_width) matrix per image, padding with -1 rows.
"""
from __future__ import annotations

import random as _pyrandom

import numpy as _np

from ..base import MXNetError
from .. import io as _io
from .. import ndarray as nd
from . import image as _img

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Base detection augmenter: __call__(src_hwc, label) -> (src, label)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Apply an image-only augmenter, passing the label through."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.__class__.__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return _img._as_np(self.augmenter(src)[0]), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one of aug_list (or none) per sample."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return _pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = _img._as_np(src)[:, ::-1].copy()
            label = label.copy()
            valid = label[:, 0] >= 0
            xmin = 1.0 - label[valid, 3]
            xmax = 1.0 - label[valid, 1]
            label[valid, 1] = xmin
            label[valid, 3] = xmax
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping enough object coverage (parity detection.py
    DetRandomCropAug; constraints mirror SSD data augmentation)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.05, 1.0), max_attempts=50):
        super().__init__(min_object_covered=min_object_covered)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        src = _img._as_np(src)
        h, w = src.shape[:2]
        for _ in range(self.max_attempts):
            area = _pyrandom.uniform(*self.area_range) * h * w
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            cw = int(round((area * ratio) ** 0.5))
            ch = int(round((area / ratio) ** 0.5))
            if cw > w or ch > h or cw <= 0 or ch <= 0:
                continue
            x0 = _pyrandom.randint(0, w - cw)
            y0 = _pyrandom.randint(0, h - ch)
            new_label = self._update_labels(label, (x0 / w, y0 / h,
                                                    (x0 + cw) / w,
                                                    (y0 + ch) / h))
            if new_label is not None:
                return src[y0:y0 + ch, x0:x0 + cw], new_label
        return src, label

    def _update_labels(self, label, crop):
        cx0, cy0, cx1, cy1 = crop
        cw, chh = cx1 - cx0, cy1 - cy0
        out = label.copy()
        valid_rows = []
        for i in range(label.shape[0]):
            if label[i, 0] < 0:
                continue
            x0, y0, x1, y1 = label[i, 1:5]
            # intersection with crop
            ix0, iy0 = max(x0, cx0), max(y0, cy0)
            ix1, iy1 = min(x1, cx1), min(y1, cy1)
            inter = max(ix1 - ix0, 0) * max(iy1 - iy0, 0)
            box_area = max(x1 - x0, 0) * max(y1 - y0, 0)
            if box_area <= 0 or inter / box_area < self.min_object_covered:
                continue
            out[i, 1] = (ix0 - cx0) / cw
            out[i, 2] = (iy0 - cy0) / chh
            out[i, 3] = (ix1 - cx0) / cw
            out[i, 4] = (iy1 - cy0) / chh
            valid_rows.append(out[i].copy())
        if not valid_rows:
            return None
        res = _np.full_like(label, -1.0)
        for i, row in enumerate(valid_rows):
            res[i] = row
        return res


class DetRandomPadAug(DetAugmenter):
    """Randomly expand the canvas and place the image inside (zoom-out)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127.5, 127.5, 127.5)):
        super().__init__(area_range=area_range)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        src = _img._as_np(src)
        h, w, c = src.shape
        for _ in range(self.max_attempts):
            scale = _pyrandom.uniform(*self.area_range)
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            nw = int(round(w * (scale * ratio) ** 0.5))
            nh = int(round(h * (scale / ratio) ** 0.5))
            if nw < w or nh < h:
                continue
            x0 = _pyrandom.randint(0, nw - w)
            y0 = _pyrandom.randint(0, nh - h)
            canvas = _np.full((nh, nw, c),
                              _np.asarray(self.pad_val)[:c],
                              dtype=src.dtype)
            canvas[y0:y0 + h, x0:x0 + w] = src
            out = label.copy()
            valid = out[:, 0] >= 0
            out[valid, 1] = (out[valid, 1] * w + x0) / nw
            out[valid, 2] = (out[valid, 2] * h + y0) / nh
            out[valid, 3] = (out[valid, 3] * w + x0) / nw
            out[valid, 4] = (out[valid, 4] * h + y0) / nh
            return canvas, out
        return src, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Standard detection chain (parity detection.py CreateDetAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(_img.ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(area_range[1], 1.0)),
                                max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(area_range[0], 1.0), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # force to final size after geometric augs
    auglist.append(DetBorrowAug(_img.ForceResizeAug(
        (data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(_img.CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            _img.ColorJitterAug(brightness, contrast, saturation)))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(_img.LightingAug(pca_noise, eigval,
                                                     eigvec)))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(_img.ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(_img.ImageIter):
    """Detection iterator (parity detection.py ImageDetIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="label",
                 **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **kwargs)
        # strip det-aug kwargs before ImageIter sees them
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, path_imgidx=path_imgidx,
                         shuffle=shuffle, part_index=part_index,
                         num_parts=num_parts, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name)
        self.det_auglist = aug_list
        first = self._peek_label()
        self.max_objects, self.object_width = first
        self.provide_label = [_io.DataDesc(
            label_name, (batch_size, self.max_objects, self.object_width))]

    def _parse_label(self, raw):
        """Flat label -> (n_obj, object_width) normalized matrix."""
        raw = _np.asarray(raw, _np.float32).reshape(-1)
        if raw.size < 2:
            raise MXNetError("ImageDetIter: label too short")
        header_width = int(raw[0])
        obj_width = int(raw[1])
        assert obj_width >= 5, "object width must be >= 5"
        body = raw[header_width:]
        n = body.size // obj_width
        return body[:n * obj_width].reshape(n, obj_width)

    def _peek_label(self):
        self.reset()
        label, _ = self.next_sample()
        mat = self._parse_label(label)
        self.reset()
        # generous padding: some images have more objects than the first
        return max(mat.shape[0] * 2, 16), mat.shape[1]

    def reshape(self, data_shape=None, label_shape=None):
        if data_shape is not None:
            self.data_shape = tuple(data_shape)[-3:] \
                if len(data_shape) == 4 else tuple(data_shape)
            self.provide_data = [_io.DataDesc(
                self.provide_data[0].name,
                (self.batch_size,) + tuple(self.data_shape))]
        if label_shape is not None:
            self.max_objects = label_shape[-2]
            self.provide_label = [_io.DataDesc(
                self.provide_label[0].name,
                (self.batch_size, self.max_objects, self.object_width))]

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = _np.zeros((batch_size, h, w, c), dtype=_np.float32)
        batch_label = _np.full(
            (batch_size, self.max_objects, self.object_width), -1.0,
            dtype=_np.float32)
        i = 0
        try:
            while i < batch_size:
                raw_label, img = self.next_sample()
                arr = _img._as_np(img)
                mat = self._parse_label(raw_label)
                pad_mat = _np.full((self.max_objects, self.object_width),
                                   -1.0, _np.float32)
                n = min(mat.shape[0], self.max_objects)
                pad_mat[:n] = mat[:n]
                for aug in self.det_auglist:
                    arr, pad_mat = aug(arr, pad_mat)
                    arr = _img._as_np(arr)
                if arr.shape[:2] != (h, w):
                    raise MXNetError(
                        "ImageDetIter: augmented image %s != data_shape %s"
                        % (arr.shape, (h, w)))
                batch_data[i] = arr.reshape(h, w, c)
                batch_label[i] = pad_mat
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = batch_size - i
        return _io.DataBatch(data=[nd.array(batch_data.transpose(0, 3, 1,
                                                                 2))],
                             label=[nd.array(batch_label)], pad=pad,
                             index=None)


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0):
    """Several DetRandomCropAug variants, one per entry when the numeric
    arguments are lists (parity detection.py:417 — the SSD multi-crop
    recipe builds one augmenter per coverage setting)."""
    del min_eject_coverage  # our DetRandomCropAug folds ejection into
    # the coverage retry loop; kept in the signature for call parity
    # normalize: any scalar argument broadcasts to the longest list
    lists = {}
    n = 1
    for name, val in [("min_object_covered", min_object_covered),
                      ("aspect_ratio_range", aspect_ratio_range),
                      ("area_range", area_range),
                      ("max_attempts", max_attempts)]:
        if isinstance(val, list):
            n = max(n, len(val))
        lists[name] = val
    augs = []
    for i in range(n):
        def pick(v):
            return v[i % len(v)] if isinstance(v, list) else v
        augs.append(DetRandomCropAug(
            min_object_covered=pick(lists["min_object_covered"]),
            aspect_ratio_range=pick(lists["aspect_ratio_range"]),
            area_range=pick(lists["area_range"]),
            max_attempts=pick(lists["max_attempts"])))
    del skip_prob
    return DetRandomSelectAug(augs, skip_prob=0) if len(augs) > 1 else augs[0]
