"""Test harness (parity: python/mxnet/test_utils.py — assert_almost_equal :443,
check_numeric_gradient :758 finite differences, check_symbolic_forward/backward
:890, check_consistency, default_context :49, random data helpers).

The trust chain mirrors the reference (SURVEY.md §4): numpy/finite-difference
oracles per op, interpreter-vs-compiled consistency, tiny-model convergence."""
from __future__ import annotations

import numpy as _np

from . import context as ctx_mod
from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError

_rng = _np.random.RandomState(1234)


def default_context():
    return ctx_mod.current_context()


def set_default_context(ctx):
    ctx_mod.Context._default_ctx.stack = [ctx]


def default_dtype():
    return _np.float32


def rand_shape_2d(dim0=10, dim1=10):
    return (_rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1),
            _rng.randint(1, dim2 + 1))


def rand_ndarray(shape, stype="default", density=None):
    if stype != "default":
        arr, _ = rand_sparse_ndarray(shape, stype, density=density)
        return arr
    return nd.array(_rng.uniform(-1, 1, size=shape))


def random_arrays(*shapes):
    arrays = [_np.array(_rng.standard_normal(s), dtype=default_dtype())
              if s else _np.array(_rng.standard_normal(), dtype=default_dtype())
              for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def same(a, b):
    return _np.array_equal(a, b)


def find_max_violation(a, b, rtol=None, atol=None):
    rtol = rtol or 1e-5
    atol = atol or 1e-20
    diff = _np.abs(a - b)
    tol = atol + rtol * _np.abs(b)
    violation = diff / (tol + 1e-20)
    loc = _np.unravel_index(_np.argmax(violation), violation.shape)
    return violation[loc], loc


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    """Parity test_utils.py:443."""
    a = a.asnumpy() if isinstance(a, nd.NDArray) else _np.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else _np.asarray(b)
    if _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True):
        return
    index, rel = find_max_violation(a, b, rtol, atol)
    raise AssertionError(
        "Error %f exceeds tolerance rtol=%f, atol=%f. Location of maximum "
        "error: %s, %s=%s, %s=%s"
        % (index, rtol, atol, str(rel), names[0],
           a.flat[0] if a.size else a, names[1], b.flat[0] if b.size else b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    return _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True)


def _parse_location(sym, location, ctx):
    if isinstance(location, dict):
        wrong = set(location.keys()) - set(sym.list_arguments())
        if wrong:
            raise ValueError("Location does not match arguments: %s" % wrong)
        location = {k: nd.array(v, ctx=ctx) if not isinstance(v, nd.NDArray)
                    else v for k, v in location.items()}
    else:
        location = {k: nd.array(v, ctx=ctx) if not isinstance(v, nd.NDArray)
                    else v for k, v in zip(sym.list_arguments(), location)}
    return location


def _parse_aux_states(sym, aux_states, ctx):
    if aux_states is None:
        return {}
    if isinstance(aux_states, dict):
        return {k: nd.array(v, ctx=ctx) if not isinstance(v, nd.NDArray) else v
                for k, v in aux_states.items()}
    return {k: nd.array(v, ctx=ctx) for k, v in
            zip(sym.list_auxiliary_states(), aux_states)}


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Central finite differences over executor forward (oracle)."""
    grads = {}
    for name in location:
        arr = location[name].asnumpy()
        grad = _np.zeros_like(arr)
        flat = arr.ravel()
        gflat = grad.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            executor.forward(is_train=use_forward_train,
                             **{name: nd.array(arr)})
            f_plus = sum(float(o.asnumpy().sum()) for o in executor.outputs)
            flat[i] = orig - eps
            executor.forward(is_train=use_forward_train,
                             **{name: nd.array(arr)})
            f_minus = sum(float(o.asnumpy().sum()) for o in executor.outputs)
            flat[i] = orig
            gflat[i] = (f_plus - f_minus) / (2 * eps)
        executor.forward(is_train=use_forward_train, **{name: nd.array(arr)})
        grads[name] = grad
    return grads


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None, dtype=_np.float32):
    """Finite differences vs autodiff backward (parity test_utils.py:758)."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    aux = _parse_aux_states(sym, aux_states, ctx)
    if grad_nodes is None:
        grad_nodes = list(location.keys())
    input_shapes = {k: v.shape for k, v in location.items()}
    arg_shapes, _, aux_shapes = sym.infer_shape(**input_shapes)
    arg_names = sym.list_arguments()
    args = {n: location.get(n, nd.zeros(s, ctx=ctx))
            for n, s in zip(arg_names, arg_shapes)}
    grad_req = {n: ("write" if n in grad_nodes else "null") for n in arg_names}
    args_grad = {n: nd.zeros(args[n].shape, ctx=ctx) for n in grad_nodes}
    executor = sym.bind(ctx, args, args_grad=args_grad, grad_req=grad_req,
                        aux_states=aux)
    executor.forward(is_train=use_forward_train)
    executor.backward()
    symbolic_grads = {k: executor.grad_dict[k].asnumpy() for k in grad_nodes}

    # numeric: perturb each grad_node input
    num_grads = {}
    for name in grad_nodes:
        arr = args[name].asnumpy().astype("float64")
        grad = _np.zeros_like(arr)
        flat = arr.ravel()
        gflat = grad.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            executor.arg_dict[name][:] = nd.array(arr.astype(dtype))
            executor.forward(is_train=use_forward_train)
            f_plus = sum(float(o.asnumpy().astype("float64").sum())
                         for o in executor.outputs)
            flat[i] = orig - numeric_eps
            executor.arg_dict[name][:] = nd.array(arr.astype(dtype))
            executor.forward(is_train=use_forward_train)
            f_minus = sum(float(o.asnumpy().astype("float64").sum())
                          for o in executor.outputs)
            flat[i] = orig
            gflat[i] = (f_plus - f_minus) / (2 * numeric_eps)
        executor.arg_dict[name][:] = nd.array(arr.astype(dtype))
        num_grads[name] = grad
    for name in grad_nodes:
        assert_almost_equal(num_grads[name], symbolic_grads[name],
                            rtol=rtol, atol=atol or 1e-4,
                            names=("NUMERICAL_%s" % name, "BACKWARD_%s" % name))


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None):
    """Parity test_utils.py:890."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    aux = _parse_aux_states(sym, aux_states, ctx)
    executor = sym.bind(ctx, location, aux_states=aux, grad_req="null")
    outputs = executor.forward(is_train=False)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]
    for output_name, expect, output in zip(sym.list_outputs(), expected,
                                           outputs):
        assert_almost_equal(expect, output.asnumpy(), rtol, atol or 1e-20,
                            ("EXPECTED_%s" % output_name,
                             "FORWARD_%s" % output_name))
    return outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None):
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    aux = _parse_aux_states(sym, aux_states, ctx)
    if isinstance(expected, (list, tuple)):
        expected = {k: v for k, v in zip(sym.list_arguments(), expected)}
    args_grad = {k: nd.zeros(v.shape, ctx=ctx)
                 for k, v in location.items() if k in expected}
    if isinstance(grad_req, str):
        grad_req = {k: grad_req if k in expected else "null"
                    for k in sym.list_arguments()}
    executor = sym.bind(ctx, location, args_grad=args_grad, grad_req=grad_req,
                        aux_states=aux)
    executor.forward(is_train=True)
    if isinstance(out_grads, (tuple, list)):
        out_grads = [nd.array(v, ctx=ctx) if not isinstance(v, nd.NDArray)
                     else v for v in out_grads]
    executor.backward(out_grads)
    grads = {k: v.asnumpy() for k, v in args_grad.items()}
    for name in expected:
        assert_almost_equal(expected[name], grads[name], rtol, atol or 1e-20,
                            ("EXPECTED_%s" % name, "BACKWARD_%s" % name))
    return args_grad


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, rtol=1e-3, atol=1e-4,
                      precision="highest"):
    """Cross-context consistency (parity check_consistency): run the same
    symbol on each ctx and compare outputs/gradients.

    ``precision``: matmul precision requested while tracing each context's
    program (jax.default_matmul_precision). The default 'highest' makes a
    TPU context compute f32 matmuls with f32 accumulation so it is
    comparable to the CPU reference; pass 'default' to test the bf16-MXU
    fast path (with a correspondingly looser tolerance ladder)."""
    import jax as _jax

    results = []
    for spec in ctx_list:
        ctx = spec["ctx"]
        shapes = {k: v for k, v in spec.items() if k != "ctx" and k != "type_dict"}
        exe = sym.simple_bind(ctx=ctx, grad_req=grad_req,
                              type_dict=spec.get("type_dict"), **shapes)
        if arg_params:
            for k, v in arg_params.items():
                if k in exe.arg_dict:
                    exe.arg_dict[k][:] = nd.array(v)
        else:
            _np.random.seed(0)
            for k, v in exe.arg_dict.items():
                v[:] = nd.array(_np.random.normal(0, scale, size=v.shape)
                                .astype(str(v.dtype)))
        with _jax.default_matmul_precision(precision or "default"):
            exe.forward(is_train=(grad_req != "null"))
            if grad_req != "null":
                exe.backward()
        results.append(exe)
    ref = results[0]
    for exe in results[1:]:
        for o_ref, o in zip(ref.outputs, exe.outputs):
            assert_almost_equal(o_ref.asnumpy(), o.asnumpy(), rtol, atol)
        if grad_req != "null":
            for name in ref.grad_dict:
                assert_almost_equal(ref.grad_dict[name].asnumpy(),
                                    exe.grad_dict[name].asnumpy(), rtol, atol)
    return results


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    ctx = ctx or default_context()
    inputs = {k: nd.array(v) for k, v in inputs.items()}
    exe = sym.bind(ctx, inputs)
    outputs = [o.asnumpy() for o in exe.forward(is_train=is_train)]
    if len(outputs) == 1:
        outputs = outputs[0]
    return outputs


# ---------------------------------------------------------------- synthetic MNIST
# Deterministic glyph digits in the real idx-ubyte format, so MNISTIter and
# the example entry points can be gated offline the way the reference gates
# LeNet/MLP on the real set (tests/python/train/test_mlp.py:82).

_SEGMENTS = {  # 7-segment encoding per digit: (t, tl, tr, m, bl, br, b)
    0: (1, 1, 1, 0, 1, 1, 1), 1: (0, 0, 1, 0, 0, 1, 0),
    2: (1, 0, 1, 1, 1, 0, 1), 3: (1, 0, 1, 1, 0, 1, 1),
    4: (0, 1, 1, 1, 0, 1, 0), 5: (1, 1, 0, 1, 0, 1, 1),
    6: (1, 1, 0, 1, 1, 1, 1), 7: (1, 0, 1, 0, 0, 1, 0),
    8: (1, 1, 1, 1, 1, 1, 1), 9: (1, 1, 1, 1, 0, 1, 1),
}


def _draw_digit(canvas, digit, y0, x0, h=16, w=10, t=2, value=255):
    seg = _SEGMENTS[int(digit)]
    m = y0 + h // 2
    if seg[0]:
        canvas[y0:y0 + t, x0:x0 + w] = value                    # top
    if seg[1]:
        canvas[y0:m, x0:x0 + t] = value                         # top-left
    if seg[2]:
        canvas[y0:m, x0 + w - t:x0 + w] = value                 # top-right
    if seg[3]:
        canvas[m - t // 2:m + t - t // 2, x0:x0 + w] = value    # middle
    if seg[4]:
        canvas[m:y0 + h, x0:x0 + t] = value                     # bottom-left
    if seg[5]:
        canvas[m:y0 + h, x0 + w - t:x0 + w] = value             # bottom-right
    if seg[6]:
        canvas[y0 + h - t:y0 + h, x0:x0 + w] = value            # bottom


def make_synthetic_mnist_arrays(n, seed=0, noise=0.15):
    """(images uint8 (n,28,28), labels uint8 (n,)): jittered 7-segment
    glyphs + salt noise — learnable to >0.97 by LeNet/MLP, non-trivial."""
    rng = _np.random.RandomState(seed)
    images = _np.zeros((n, 28, 28), _np.uint8)
    labels = rng.randint(0, 10, n).astype(_np.uint8)
    for i in range(n):
        y0 = 6 + rng.randint(-3, 4)
        x0 = 9 + rng.randint(-4, 5)
        _draw_digit(images[i], labels[i], y0, x0)
        mask = rng.rand(28, 28) < noise
        images[i][mask] = _np.maximum(
            images[i][mask], rng.randint(0, 160, mask.sum()))
    return images, labels


def _write_idx(path, arr, is_image):
    import struct
    with open(path, "wb") as f:
        if is_image:
            f.write(struct.pack(">IIII", 0x00000803, arr.shape[0], 28, 28))
        else:
            f.write(struct.pack(">II", 0x00000801, arr.shape[0]))
        f.write(arr.astype(_np.uint8).tobytes())


def make_synthetic_mnist_idx(directory, n_train=2048, n_test=512, seed=0):
    """Write train/t10k idx-ubyte files under `directory`; returns it."""
    import os
    os.makedirs(directory, exist_ok=True)
    tri, trl = make_synthetic_mnist_arrays(n_train, seed=seed)
    tei, tel = make_synthetic_mnist_arrays(n_test, seed=seed + 1)
    _write_idx(os.path.join(directory, "train-images-idx3-ubyte"), tri, True)
    _write_idx(os.path.join(directory, "train-labels-idx1-ubyte"), trl, False)
    _write_idx(os.path.join(directory, "t10k-images-idx3-ubyte"), tei, True)
    _write_idx(os.path.join(directory, "t10k-labels-idx1-ubyte"), tel, False)
    return directory


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Apply a numpy reduce function over (possibly several) axes with
    keepdims semantics (parity test_utils.py:383 — the oracle helper the
    reference's reduction tests are written against)."""
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else list(range(dat.ndim))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        shape = list(dat.shape)
        for i in axis:
            shape[i] = 1
        ret = ret.reshape(tuple(shape))
    return ret


def _dense_to_sparse(dense, stype):
    from .ndarray import sparse as _sp
    if stype == "csr":
        return _sp.csr_matrix(dense)
    if stype == "row_sparse":
        return _sp.row_sparse_array(dense)
    raise ValueError("unknown storage type %s" % stype)


def rand_sparse_ndarray(shape, stype, density=None, dtype=None):
    """Random sparse NDArray + its dense numpy twin (parity
    test_utils.py:244). Draws from the module's seeded _rng like the
    other random helpers."""
    density = 0.3 if density is None else density
    dtype = _np.float32 if dtype is None else _np.dtype(dtype)
    dense = _rng.uniform(-1, 1, size=shape).astype(dtype)
    dense[_rng.uniform(size=shape) > density] = 0
    return _dense_to_sparse(dense, stype), dense


def create_sparse_array(shape, stype, data_init=None, density=0.5,
                        dtype=None):
    """Sparse NDArray filled from data_init or random (parity
    test_utils.py:324)."""
    dtype = _np.float32 if dtype is None else _np.dtype(dtype)
    if data_init is not None:
        dense = _np.full(shape, data_init, dtype)
    else:
        dense = _rng.uniform(0, 1, size=shape).astype(dtype)
        dense[_rng.uniform(size=shape) > density] = 0
    return _dense_to_sparse(dense, stype)


# --------------------------------------------------------- small helpers
# (parity: the reference test_utils.py long tail — tolerance ladders,
# nan-tolerant comparison, env/stderr scoping, misc random helpers)

_DTYPE_TOL = {_np.dtype(_np.float16): (1e-2, 1e-1),
              _np.dtype(_np.float32): (1e-4, 1e-3),
              _np.dtype(_np.float64): (1e-5, 1e-8)}


def get_rtol(rtol=None):
    return 1e-5 if rtol is None else rtol


def get_atol(atol=None):
    return 1e-20 if atol is None else atol


def random_sample(population, k):
    """Sample without replacement preserving population order (parity
    test_utils.py random_sample)."""
    import random as _random

    picked = _random.sample(list(population), k)
    return [x for x in population if x in set(picked)][:k]


def shuffle_csr_column_indices(csr):
    """Permute the column indices within each row of a CSR (tests that
    ops tolerate unsorted indices)."""
    import numpy as _np2
    arr = csr.asnumpy()
    return arr  # dense round-trip loses index order by construction


def almost_equal_ignore_nan(a, b, rtol=None, atol=None):
    """Elementwise closeness where PAIRED NaNs count as equal."""
    a, b = _np.copy(a), _np.copy(b)
    nan_mask = _np.logical_or(_np.isnan(a), _np.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    return _np.allclose(a, b, rtol=get_rtol(rtol), atol=get_atol(atol))


def assert_almost_equal_ignore_nan(a, b, rtol=None, atol=None, names=("a", "b")):
    if not almost_equal_ignore_nan(a, b, rtol, atol):
        raise AssertionError("%s and %s differ beyond tolerance "
                             "(nan-masked)" % names)


def same_array(array1, array2):
    """Whether two NDArrays share (or at least mirror) the same values
    after an in-place bump — the reference's buffer-aliasing probe."""
    array1[:] = array1.asnumpy() + 1
    if not _np.array_equal(array1.asnumpy(), array2.asnumpy()):
        array1[:] = array1.asnumpy() - 1
        return False
    array1[:] = array1.asnumpy() - 1
    return True


def assign_each(input_arr, function):
    """Elementwise map via numpy (parity assign_each)."""
    return _np.vectorize(function)(input_arr.asnumpy()
                                   if hasattr(input_arr, "asnumpy")
                                   else input_arr)


def assign_each2(input1, input2, function):
    return _np.vectorize(function)(
        input1.asnumpy() if hasattr(input1, "asnumpy") else input1,
        input2.asnumpy() if hasattr(input2, "asnumpy") else input2)


def create_sparse_array_zd(shape, stype, density=0.05, **kwargs):
    """Sparse random array allowing zero density (parity
    create_sparse_array_zd)."""
    del kwargs
    dense = _np.random.rand(*shape) * (_np.random.rand(*shape) < density)
    from .ndarray import array as _nd_array
    return _nd_array(dense.astype("float32")).tostype(stype)


def rand_shape_nd(ndim, dim=10):
    return tuple(_np.random.randint(1, dim + 1, size=ndim))


def list_gpus():
    """Ordinals of CUDA GPUs — none on a TPU host (parity list_gpus)."""
    return []


def download(url, fname=None, dirname=None, overwrite=False):
    """Parity stub: this environment has no egress; the reference's
    download() fetches test datasets. Raises with a clear message."""
    raise MXNetError("download(%r): no network egress in this environment; "
                     "provide local files instead" % url)


def get_mnist():
    """Synthetic MNIST-shaped blobs (the reference downloads real MNIST;
    offline parity keeps the SHAPES and dtype contract)."""
    rng = _np.random.RandomState(42)
    return {"train_data": rng.rand(512, 1, 28, 28).astype("float32"),
            "train_label": rng.randint(0, 10, 512).astype("float32"),
            "test_data": rng.rand(128, 1, 28, 28).astype("float32"),
            "test_label": rng.randint(0, 10, 128).astype("float32")}


class discard_stderr:
    """Context manager silencing fd-level stderr (parity discard_stderr)."""

    def __enter__(self):
        import os as _os
        self._stderr_fno = 2
        self._saved = _os.dup(self._stderr_fno)
        self._devnull = _os.open(_os.devnull, _os.O_WRONLY)
        _os.dup2(self._devnull, self._stderr_fno)
        return self

    def __exit__(self, *args):
        import os as _os
        _os.dup2(self._saved, self._stderr_fno)
        _os.close(self._devnull)
        _os.close(self._saved)


def set_env_var(key, val, default_val=""):
    """Set an env var returning the previous value (parity set_env_var)."""
    import os as _os
    prev = _os.environ.get(key, default_val)
    _os.environ[key] = str(val)
    return prev


def retry(n):
    """Decorator retrying a flaky test up to n times (parity retry)."""
    import functools

    def decorate(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            last = None
            for _ in range(max(int(n), 1)):
                try:
                    return fn(*args, **kwargs)
                except AssertionError as e:
                    last = e
            raise last
        return wrapped
    return decorate


def check_speed(sym, location=None, ctx=None, N=20, grad_req="write",
                **kwargs):
    """Rough per-forward-backward wall time for a symbol (parity
    check_speed: the timing harness benchmark scripts import)."""
    import time as _time

    from .context import cpu as _cpu
    from .ndarray import array as _nd_array, zeros as _nd_zeros

    ctx = ctx or _cpu()
    shapes, _, _ = sym.infer_shape(**{k: v.shape if hasattr(v, "shape")
                                      else v for k, v in
                                      (location or {}).items()})
    args = {}
    for name, shape in zip(sym.list_arguments(), shapes):
        if location and name in location:
            v = location[name]
            args[name] = v if hasattr(v, "asnumpy") else _nd_array(v)
        else:
            args[name] = _nd_array(
                _np.random.rand(*shape).astype("float32"))
    grads = {n: _nd_zeros(v.shape) for n, v in args.items()}
    exe = sym.bind(ctx, args, args_grad=grads, grad_req=grad_req)
    exe.forward(is_train=True)
    exe.backward()
    [o.wait_to_read() for o in exe.outputs]
    t0 = _time.perf_counter()
    for _ in range(N):
        exe.forward(is_train=True)
        exe.backward()
    [o.asnumpy() for o in exe.outputs]
    return (_time.perf_counter() - t0) / N


class FixedLatencyIter:
    """DataIter wrapper adding a fixed per-batch fetch latency — models a
    remote-storage/record-shard producer for pipeline tests and benches
    (the regime ``io.DevicePrefetchIter`` exists to hide)."""

    def __init__(self, inner, delay_s):
        import time as _time_mod
        self._time = _time_mod
        self._inner = inner
        self._delay = delay_s
        self.batch_size = inner.batch_size
        self.provide_data = inner.provide_data
        self.provide_label = inner.provide_label

    def __iter__(self):
        return self

    def reset(self):
        self._inner.reset()

    def next(self):
        self._time.sleep(self._delay)
        return self._inner.next()

    def __next__(self):
        return self.next()
