"""mx.rtc — runtime-compiled custom kernels.

Parity: include/mxnet/mxrtc.h + python/mxnet/rtc.py, where the reference
JIT-compiles CUDA C source via NVRTC and launches it on NDArrays.

TPU-native design: there is no "source string -> PTX" path on TPU; the
honest equivalent is a Python kernel body compiled by the XLA/Pallas
toolchain. ``CudaModule``-style source strings are not supported; instead
``Rtc`` takes a Python callable over jax arrays — by default jit-compiled
(XLA fuses it), or lowered as a Pallas TPU kernel when ``pallas=True`` and
a ``pallas_call`` spec is supplied. The push-style launch API matches the
reference's ``rtc.push(ins, outs, grid, block)`` shape minus the
grid/block geometry, which has no meaning under XLA's tiling.
"""
from __future__ import annotations

import jax

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["Rtc"]


class Rtc:
    """A runtime-compiled kernel over NDArrays.

    Parameters
    ----------
    name : str
        Kernel name (diagnostic only).
    fn : callable(*jax_arrays) -> jax array or tuple
        The kernel body. Traced and compiled on first push per shape set.
    pallas : bool
        If True, ``fn`` is expected to already be a pallas_call-wrapped
        kernel (see /opt/skills/guides/pallas_guide.md); it is invoked
        directly so its BlockSpecs control tiling.
    """

    def __init__(self, name, fn, pallas=False):
        if isinstance(fn, str):
            raise MXNetError(
                "mx.rtc on TPU takes a Python kernel function, not CUDA "
                "source (NVRTC has no TPU equivalent; write a jax/pallas "
                "kernel body instead)")
        self.name = name
        self._fn = fn if pallas else jax.jit(fn)

    def push(self, ins, outs=None, *_grid_block):
        """Run the kernel. ``ins`` are NDArrays; results are returned and,
        when ``outs`` is given, also written into those NDArrays (the
        reference's output-argument convention)."""
        args = [x._data if isinstance(x, NDArray) else x for x in ins]
        res = self._fn(*args)
        if not isinstance(res, (tuple, list)):
            res = (res,)
        if outs is not None:
            if len(outs) != len(res):
                raise MXNetError("rtc %s: %d outputs for %d results"
                                 % (self.name, len(outs), len(res)))
            for dst, val in zip(outs, res):
                dst._data = val
        return [NDArray(v, ins[0].context if ins else None) for v in res]

    __call__ = push
