"""Neural-network layer ops: conv/pool/BN/FC/activations/losses/sequence ops.

Parity with the reference's legacy OperatorProperty layer set (SURVEY.md §2.3,
src/operator/{convolution,pooling,batch_norm,fully_connected,activation,dropout,
softmax_output,leaky_relu,lrn,concat,slice_channel,pad,upsampling,instance_norm,
l2_normalization,sequence_*,regression_output,make_loss}-inl.h). TPU-native: each
lowers to a handful of XLA HLOs (conv_general_dilated, reduce_window, dot_general)
and the cuDNN wrapper layer (src/operator/cudnn_*) disappears into the compiler.
Loss-head ops (SoftmaxOutput etc.) use jax.custom_vjp to encode the reference
semantics that backward ignores incoming head gradients.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .registry import Required, register

# ---------------------------------------------------------------- FullyConnected


def _fully_connected(a, data, weight, bias=None):
    if a.get("flatten", True):
        x = data.reshape(data.shape[0], -1)
    else:
        x = data  # apply along the last axis (Gluon Dense flatten=False)
    out = jnp.dot(x, weight.T)
    if bias is not None:
        out = out + bias
    return out


register("FullyConnected", _fully_connected,
         arg_names=lambda a: ["data", "weight"] if a.get("no_bias") else
         ["data", "weight", "bias"],
         attrs={"num_hidden": Required(int), "no_bias": False,
                "flatten": True})

# ---------------------------------------------------------------- Convolution

_CONV_DNUMS = {1: ("NCW", "OIW", "NCW"),
               2: ("NCHW", "OIHW", "NCHW"),
               3: ("NCDHW", "OIDHW", "NCDHW")}


def _tup(v, n, default):
    v = tuple(v) if v else ()
    if len(v) < n:
        v = v + (default,) * (n - len(v))
    return v[:n]


def _convolution(a, data, weight, bias=None):
    nd = len(a.kernel)
    stride = _tup(a.stride, nd, 1)
    dilate = _tup(a.dilate, nd, 1)
    pad = _tup(a.pad, nd, 0)
    dnums = _CONV_DNUMS[nd]
    channels_last = nd == 2 and a.get("layout") == "NHWC"
    if channels_last:
        # channels-last activations (the compile pipeline's `layout`
        # transform): the WEIGHT keeps its OIHW storage — only the
        # activation layout moves, so bind dicts/checkpoints are
        # untouched and the rewrite never transposes parameters
        dnums = ("NHWC", "OIHW", "NHWC")
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dnums,
        feature_group_count=int(a.num_group),
        preferred_element_type=None)
    if bias is not None:
        out = out + (bias if channels_last
                     else bias.reshape((1, -1) + (1,) * nd))
    return out


register("Convolution", _convolution,
         arg_names=lambda a: ["data", "weight"] if a.get("no_bias") else
         ["data", "weight", "bias"],
         attrs={"kernel": Required(tuple), "stride": (), "dilate": (), "pad": (),
                "num_filter": Required(int), "num_group": 1, "no_bias": False,
                "workspace": 1024, "cudnn_tune": None, "cudnn_off": False,
                "layout": None},
         aliases=("Convolution_v1",))


def _deconvolution(a, data, weight, bias=None):
    """Transposed convolution as the explicit gradient-of-conv form:
    lhs_dilation=stride + spatially-flipped weight. Weight layout is the
    reference's (C_in, C_out/g, *k) (deconvolution-inl.h); verified
    element-for-element against torch.nn.functional.conv_transpose
    across channel/stride/pad/output_padding/group combinations
    (tests/test_operator_semantics.py)."""
    nd = len(a.kernel)
    k = tuple(int(x) for x in a.kernel)
    stride = _tup(a.stride, nd, 1)
    dilate = _tup(a.dilate, nd, 1)
    pad = _tup(a.pad, nd, 0)
    adj = _tup(a.adj, nd, 0)
    g = int(a.num_group)
    ke = tuple(dilate[i] * (k[i] - 1) + 1 for i in range(nd))  # effective
    if a.target_shape:
        tgt = _tup(a.target_shape, nd, 0)
        adj = tuple(
            tgt[i] - ((data.shape[2 + i] - 1) * stride[i]
                      - 2 * pad[i] + ke[i])
            for i in range(nd))
    ci = weight.shape[0]
    co = weight.shape[1] * g
    w = weight[(slice(None), slice(None)) + (slice(None, None, -1),) * nd]
    # (C_in, C_out/g, *k) -> blockwise (C_out, C_in/g, *k) so XLA's grouped
    # conv sees the standard O/I layout
    w = w.reshape((g, ci // g, co // g) + k)
    w = jnp.swapaxes(w, 1, 2).reshape((co, ci // g) + k)
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * nd,
        padding=[(ke[i] - 1 - pad[i], ke[i] - 1 - pad[i] + adj[i])
                 for i in range(nd)],
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=_CONV_DNUMS[nd],
        feature_group_count=g)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


register("Deconvolution", _deconvolution,
         arg_names=lambda a: ["data", "weight"] if a.get("no_bias", True) else
         ["data", "weight", "bias"],
         attrs={"kernel": Required(tuple), "stride": (), "dilate": (), "pad": (),
                "adj": (), "target_shape": (), "num_filter": Required(int),
                "num_group": 1, "no_bias": True, "workspace": 512,
                "cudnn_tune": None, "cudnn_off": False, "layout": None})

# ---------------------------------------------------------------- Pooling


def _pool_pads(in_shape, kernel, stride, pad, convention):
    """Per-dim (lo, hi) padding; 'full' (ceil) convention pads extra on the high side."""
    pads = []
    for x, k, s, p in zip(in_shape, kernel, stride, pad):
        if convention == "full":
            out = -(-(x + 2 * p - k) // s) + 1  # ceil
        else:
            out = (x + 2 * p - k) // s + 1
        needed = max((out - 1) * s + k - x - p, p)
        pads.append((p, needed))
    return pads


def _pooling(a, data):
    nd = data.ndim - 2
    channels_last = nd == 2 and a.get("layout") == "NHWC"
    spatial = data.shape[1:3] if channels_last else data.shape[2:]
    if a.global_pool:
        kernel = spatial
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = _tup(a.kernel, nd, 1)
        stride = _tup(a.stride, nd, 1)
        pad = _tup(a.pad, nd, 0)
    sp_pads = _pool_pads(spatial, kernel, stride, pad,
                         a.pooling_convention)
    if channels_last:
        pads = [(0, 0)] + sp_pads + [(0, 0)]
        dims = (1,) + tuple(kernel) + (1,)
        strides = (1,) + tuple(stride) + (1,)
    else:
        pads = [(0, 0), (0, 0)] + sp_pads
        dims = (1, 1) + tuple(kernel)
        strides = (1, 1) + tuple(stride)
    if a.pool_type == "max":
        # scalar init keeps XLA's reduce-window-max pattern (autodiff-able)
        return lax.reduce_window(data, -jnp.inf, lax.max, dims, strides, pads)
    s = lax.reduce_window(data, 0.0, lax.add, dims, strides, pads)
    if a.pool_type == "sum":
        return s
    # avg: divide by full window size (reference mshadow pool includes padding)
    denom = 1
    for k in kernel:
        denom *= k
    return s / jnp.asarray(denom, data.dtype)


register("Pooling", _pooling,
         attrs={"kernel": (), "pool_type": "max", "global_pool": False,
                "stride": (), "pad": (), "pooling_convention": "valid",
                "cudnn_off": False, "layout": None},
         aliases=("Pooling_v1",))

# ---------------------------------------------------------------- BatchNorm


def _batch_norm(a, data, gamma, beta, moving_mean, moving_var):
    ax = int(a.get("axis", 1))
    red = tuple(i for i in range(data.ndim) if i != ax)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    g = jnp.ones_like(gamma) if a.fix_gamma else gamma
    if a.use_global_stats or not a.get("__is_train__", False):
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    else:
        # single-pass stats: sum and sum-of-squares fuse into ONE
        # multi-output reduction that reads the (bf16) activation once with
        # the f32 convert inlined. The two-pass jnp.var form needs the f32
        # activation twice, which makes XLA materialize a full f32 copy of
        # every conv output — ~2x the training step's HBM traffic.
        n = 1.0
        for i in red:
            n *= data.shape[i]
        s1 = jnp.sum(data, axis=red, dtype=jnp.float32)
        s2 = jnp.sum(jnp.square(data.astype(jnp.float32)), axis=red)
        mean32 = s1 / n
        var32 = jnp.maximum(s2 / n - jnp.square(mean32), 0.0)
        mean = mean32.astype(data.dtype)
        var = var32.astype(data.dtype)
        m = a.momentum
        new_mm = m * moving_mean + (1 - m) * lax.stop_gradient(mean)
        new_mv = m * moving_var + (1 - m) * lax.stop_gradient(var)
    inv = lax.rsqrt(var.astype(jnp.float32) + a.eps).astype(data.dtype)
    out = (data - mean.reshape(bshape)) * (g * inv).reshape(bshape) + beta.reshape(bshape)
    if a.output_mean_var:
        return out, mean, var, new_mm, new_mv
    return out, new_mm, new_mv


register("BatchNorm", _batch_norm,
         arg_names=["data", "gamma", "beta", "moving_mean", "moving_var"],
         aux_names=["moving_mean", "moving_var"],
         attrs={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                "use_global_stats": False, "output_mean_var": False, "axis": 1,
                "__is_train__": False},
         num_outputs=lambda a: 3 if a.output_mean_var else 1,
         aliases=("BatchNorm_v1",))


def _layer_norm(a, data, gamma, beta):
    """Normalize over one axis with learned scale/shift (the transformer
    family's workhorse; the reference gained nn.LayerNorm post-0.11 —
    src/operator/nn/layer_norm.cc in later MXNet, whose extra outputs are
    (mean, STD)). Statistics follow _batch_norm's traffic discipline: one
    multi-output sum/sum-of-squares reduction with f32 accumulation and
    the convert inlined, never a materialized f32 copy of the input."""
    ax = int(a.get("axis", -1)) % data.ndim
    n = data.shape[ax]
    s1 = jnp.sum(data, axis=ax, keepdims=True, dtype=jnp.float32)
    s2 = jnp.sum(jnp.square(data.astype(jnp.float32)), axis=ax,
                 keepdims=True)
    mean = s1 / n
    # clamp: the E[x^2]-E[x]^2 cancellation can go slightly negative
    var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
    inv = lax.rsqrt(var + a.eps)
    bshape = tuple(data.shape[ax] if i == ax else 1
                   for i in range(data.ndim))
    out32 = (data.astype(jnp.float32) - mean) * inv \
        * gamma.astype(jnp.float32).reshape(bshape) \
        + beta.astype(jnp.float32).reshape(bshape)
    out = out32.astype(data.dtype)
    if a.output_mean_var:
        return (out,
                jnp.squeeze(mean, ax).astype(data.dtype),
                jnp.squeeze(jnp.sqrt(var + a.eps), ax).astype(data.dtype))
    return out


register("LayerNorm", _layer_norm,
         arg_names=["data", "gamma", "beta"],
         attrs={"eps": 1e-5, "axis": -1, "output_mean_var": False},
         num_outputs=lambda a: 3 if a.output_mean_var else 1)

# ---------------------------------------------------------------- activations


def _activation(a, x):
    t = a.act_type
    if t == "relu":
        return jnp.maximum(x, 0)
    if t == "sigmoid":
        return jax.nn.sigmoid(x)
    if t == "tanh":
        return jnp.tanh(x)
    if t == "softrelu":
        return jax.nn.softplus(x)
    raise ValueError("unknown act_type %s" % t)


register("Activation", _activation, attrs={"act_type": Required(str)})


def _leaky_relu(a, x, gamma=None):
    t = a.act_type
    if t == "leaky":
        return jnp.where(x > 0, x, a.slope * x)
    if t == "elu":
        return jnp.where(x > 0, x, a.slope * (jnp.exp(x) - 1))
    if t == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x > 0, x, g * x)
    if t == "rrelu":
        slope = (a.lower_bound + a.upper_bound) / 2.0
        return jnp.where(x > 0, x, slope * x)
    raise ValueError("unknown act_type %s" % t)


register("LeakyReLU", _leaky_relu,
         arg_names=lambda a: ["data", "gamma"] if a.get("act_type") == "prelu"
         else ["data"],
         attrs={"act_type": "leaky", "slope": 0.25, "lower_bound": 0.125,
                "upper_bound": 0.334})

# ---------------------------------------------------------------- softmax family
register("softmax", lambda a, x: jax.nn.softmax(
    x / (a.temperature or 1.0), axis=int(a.axis)),
    attrs={"axis": -1, "temperature": None})
register("log_softmax", lambda a, x: jax.nn.log_softmax(
    x / (a.temperature or 1.0), axis=int(a.axis)),
    attrs={"axis": -1, "temperature": None})


def _softmax_activation(a, x):
    if a.mode == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


register("SoftmaxActivation", _softmax_activation, attrs={"mode": "instance"})


# -- SoftmaxOutput: forward = softmax(data); backward = (p - target) * scale,
#    ignoring head gradients (reference src/operator/softmax_output-inl.h).
@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _softmax_output_core(a, data, label):
    return _softmax_fwd_only(a, data)


def _softmax_fwd_only(a, data):
    if a.multi_output:
        return jax.nn.softmax(data, axis=1)
    if data.ndim > 2 and not a.preserve_shape:
        return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)
    return jax.nn.softmax(data, axis=-1)


def _softmax_output_fwd(a, data, label):
    out = _softmax_fwd_only(a, data)
    return out, (out, label)


def _softmax_output_bwd(a, res, g):
    p, label = res
    axis = 1 if a.multi_output else p.ndim - 1
    if label.shape == p.shape:
        target = label
        valid = jnp.ones(label.shape[:1], p.dtype)
    else:
        idx = label.astype(jnp.int32)
        target = jax.nn.one_hot(idx, p.shape[axis], dtype=p.dtype, axis=axis)
        if a.use_ignore:
            mask = (idx != int(a.ignore_label)).astype(p.dtype)
            target = jnp.where(jnp.expand_dims(mask, axis).astype(bool), target, p)
            valid = mask
        else:
            valid = jnp.ones(idx.shape, p.dtype)
    grad = (p - target) * a.grad_scale
    if a.normalization == "batch":
        grad = grad / p.shape[0]
    elif a.normalization == "valid":
        grad = grad / jnp.maximum(jnp.sum(valid), 1.0)
    return grad.astype(p.dtype), jnp.zeros_like(label)


_softmax_output_core.defvjp(_softmax_output_fwd, _softmax_output_bwd)

register("SoftmaxOutput", lambda a, data, label: _softmax_output_core(a, data, label),
         arg_names=["data", "label"],
         attrs={"grad_scale": 1.0, "ignore_label": -1.0, "multi_output": False,
                "use_ignore": False, "preserve_shape": False,
                "normalization": "null", "out_grad": False, "smooth_alpha": 0.0},
         loss_like=True, aliases=("Softmax",))


def _softmax_cross_entropy(a, data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    idx = label.astype(jnp.int32)
    return -jnp.sum(jnp.take_along_axis(logp, idx[:, None], axis=-1))


register("softmax_cross_entropy", _softmax_cross_entropy,
         arg_names=["data", "label"], attrs={})

# ---------------------------------------------------------------- regression heads


def _regression(name, link, grad_fn):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
    def core(a, data, label):
        return link(data)

    def fwd(a, data, label):
        out = link(data)
        return out, (out, label)

    def bwd(a, res, g):
        out, label = res
        # the reference reshapes label to the prediction's shape
        # (regression_output-inl.h), so (b,) labels pair with (b, 1) preds
        # without broadcasting into a (b, b) gradient
        lab = label.reshape(out.shape) if label.shape != out.shape else label
        grad = grad_fn(out, lab) * a.grad_scale
        return grad.astype(out.dtype), jnp.zeros_like(label)

    core.defvjp(fwd, bwd)
    register(name, lambda a, d, l: core(a, d, l), arg_names=["data", "label"],
             attrs={"grad_scale": 1.0}, loss_like=True)


_regression("LinearRegressionOutput", lambda x: x, lambda o, l: o - l)
_regression("LogisticRegressionOutput", jax.nn.sigmoid, lambda o, l: o - l)
_regression("MAERegressionOutput", lambda x: x, lambda o, l: jnp.sign(o - l))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _make_loss_core(a, data):
    return data


def _make_loss_fwd(a, data):
    return data, data.shape


def _make_loss_bwd(a, shape, g):
    scale = a.grad_scale
    if a.normalization == "batch":
        scale = scale / shape[0]
    elif a.normalization == "valid":
        scale = scale / max(1, int(_np.prod(shape)))
    return (jnp.full(shape, scale, jnp.float32),)


_make_loss_core.defvjp(_make_loss_fwd, _make_loss_bwd)
register("MakeLoss", lambda a, x: _make_loss_core(a, x),
         attrs={"grad_scale": 1.0, "valid_thresh": 0.0, "normalization": "null"},
         loss_like=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _svm_core(a, data, label):
    return data


def _svm_fwd(a, data, label):
    return data, (data, label)


def _svm_bwd(a, res, g):
    data, label = res
    idx = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(idx, data.shape[-1], dtype=data.dtype)
    if a.use_linear:
        viol = ((1 - onehot * 2) * data + a.margin > 0).astype(data.dtype)
        grad = viol * (1 - onehot * 2)
    else:
        dist = (1 - onehot * 2) * data + a.margin
        grad = 2 * jnp.maximum(dist, 0) * (1 - onehot * 2)
    return (grad * a.regularization_coefficient).astype(data.dtype), jnp.zeros_like(label)


_svm_core.defvjp(_svm_fwd, _svm_bwd)
register("SVMOutput", lambda a, d, l: _svm_core(a, d, l), arg_names=["data", "label"],
         attrs={"margin": 1.0, "regularization_coefficient": 1.0, "use_linear": False},
         loss_like=True)

# ---------------------------------------------------------------- Dropout


def _dropout(a, rng, x):
    if not a.get("__is_train__", False) or a.p <= 0:
        return x
    keep = 1.0 - a.p
    mask = jax.random.bernoulli(rng, keep, x.shape).astype(x.dtype) / keep
    return x * mask


register("Dropout", _dropout, attrs={"p": 0.5, "__is_train__": False},
         needs_rng=True)

# ---------------------------------------------------------------- normalization


def _lrn(a, x):
    n = int(a.nsize)
    sq = jnp.square(x)
    pad = [(0, 0), (n // 2, n // 2), (0, 0), (0, 0)][: x.ndim]
    while len(pad) < x.ndim:
        pad.append((0, 0))
    # literal init value: a traced init breaks reverse-mode autodiff of
    # reduce_window (same constraint as Pooling above)
    s = lax.reduce_window(sq, 0.0, lax.add,
                          (1, n) + (1,) * (x.ndim - 2), (1,) * x.ndim, pad)
    return x * jnp.power(a.knorm + (a.alpha / n) * s, -a.beta)


register("LRN", _lrn,
         attrs={"alpha": 1e-4, "beta": 0.75, "knorm": 2.0, "nsize": Required(int)})


def _instance_norm(a, x, gamma, beta):
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) * lax.rsqrt(var + a.eps) * gamma.reshape(bshape) + beta.reshape(bshape)


register("InstanceNorm", _instance_norm, arg_names=["data", "gamma", "beta"],
         attrs={"eps": 1e-3})


def _l2_normalization(a, x):
    if a.mode == "channel":
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + a.eps)
    elif a.mode == "spatial":
        red = tuple(range(2, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=True) + a.eps)
    else:  # instance
        red = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=True) + a.eps)
    return x / norm


register("L2Normalization", _l2_normalization,
         attrs={"eps": 1e-10, "mode": "instance"})

# ---------------------------------------------------------------- concat / split
register("Concat", lambda a, *xs: jnp.concatenate(xs, axis=int(a.dim)),
         variadic="num_args", attrs={"num_args": Required(int), "dim": 1},
         aliases=("concat",))


def _slice_channel(a, x):
    ax = int(a.axis)
    parts = jnp.split(x, int(a.num_outputs), axis=ax)
    if a.squeeze_axis:
        parts = [jnp.squeeze(p, axis=ax) for p in parts]
    return tuple(parts)


register("SliceChannel", _slice_channel,
         attrs={"num_outputs": Required(int), "axis": 1, "squeeze_axis": False},
         num_outputs=lambda a: int(a.num_outputs), aliases=("split",))

# ---------------------------------------------------------------- pad / upsample


def _pad(a, x):
    pw = a.pad_width
    pairs = [(int(pw[2 * i]), int(pw[2 * i + 1])) for i in range(x.ndim)]
    if a.mode == "constant":
        return jnp.pad(x, pairs, constant_values=a.constant_value)
    mode = {"edge": "edge", "reflect": "reflect"}[a.mode]
    return jnp.pad(x, pairs, mode=mode)


register("Pad", _pad,
         attrs={"mode": Required(str), "pad_width": Required(tuple),
                "constant_value": 0.0},
         aliases=("pad",))


def _upsampling(a, *xs):
    s = int(a.scale)
    if a.sample_type == "nearest":
        outs = []
        target = None
        for x in xs:
            up = jnp.repeat(jnp.repeat(x, s, axis=2), s, axis=3)
            if target is None:
                target = up.shape[2:]
            outs.append(up)
        if len(outs) == 1:
            return outs[0]
        return jnp.concatenate(outs, axis=1)
    x = xs[0]
    new = (x.shape[0], x.shape[1], x.shape[2] * s, x.shape[3] * s)
    return jax.image.resize(x, new, method="bilinear")


register("UpSampling", _upsampling, variadic="num_args",
         attrs={"num_args": 1, "scale": Required(int), "sample_type": "nearest",
                "num_filter": 0, "multi_input_mode": "concat", "workspace": 512})


def _crop_op(a, *xs):
    x = xs[0]
    if len(xs) == 2:
        h, w = xs[1].shape[2], xs[1].shape[3]
    else:
        h, w = int(a.h_w[0]), int(a.h_w[1])
    if a.center_crop:
        y0 = (x.shape[2] - h) // 2
        x0 = (x.shape[3] - w) // 2
    else:
        y0, x0 = int(a.offset[0]), int(a.offset[1])
    return x[:, :, y0:y0 + h, x0:x0 + w]


register("Crop", _crop_op, variadic="num_args",
         attrs={"num_args": 1, "offset": (0, 0), "h_w": (0, 0),
                "center_crop": False})

# ---------------------------------------------------------------- sequence ops


def _seq_iota(data):
    # data layout (T, N, ...) -- axis 0 is time (reference sequence_*-inl.h)
    T = data.shape[0]
    shape = (T,) + (1,) * (data.ndim - 1)
    return jnp.arange(T).reshape(shape)


def _sequence_last(a, data, sequence_length=None):
    if not a.use_sequence_length or sequence_length is None:
        return data[-1]
    idx = (sequence_length.astype(jnp.int32) - 1)  # (N,)
    return jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)).astype(jnp.int32),
        axis=0)[0]


register("SequenceLast", _sequence_last,
         arg_names=lambda a: ["data", "sequence_length"]
         if a.get("use_sequence_length") else ["data"],
         attrs={"use_sequence_length": False})


def _sequence_mask(a, data, sequence_length=None):
    if not a.use_sequence_length or sequence_length is None:
        return data
    t = _seq_iota(data)
    lens = sequence_length.reshape((1, -1) + (1,) * (data.ndim - 2))
    return jnp.where(t < lens, data, jnp.asarray(a.value, data.dtype))


register("SequenceMask", _sequence_mask,
         arg_names=lambda a: ["data", "sequence_length"]
         if a.get("use_sequence_length") else ["data"],
         attrs={"use_sequence_length": False, "value": 0.0})


def _sequence_reverse(a, data, sequence_length=None):
    if not a.use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    t = _seq_iota(data)
    lens = sequence_length.reshape((1, -1) + (1,) * (data.ndim - 2)).astype(jnp.int32)
    src = jnp.where(t < lens, lens - 1 - t, t)
    return jnp.take_along_axis(data, jnp.broadcast_to(src, data.shape), axis=0)


register("SequenceReverse", _sequence_reverse,
         arg_names=lambda a: ["data", "sequence_length"]
         if a.get("use_sequence_length") else ["data"],
         attrs={"use_sequence_length": False})

# ---------------------------------------------------------------- misc
register("IdentityAttachKLSparseReg", lambda a, x: x,
         attrs={"sparseness_target": 0.1, "penalty": 0.001, "momentum": 0.9})

# ------------------------------------------------------- arg-shape inference
# fills parameter shapes from the data shape (see registry.OpDef.infer_args)
from .registry import get_op as _get_op  # noqa: E402


def _prod(xs):
    p = 1
    for x in xs:
        p *= x
    return p


def _fc_infer(a, shapes):
    data = shapes[0]
    d = data[-1] if not a.get("flatten", True) else _prod(data[1:])
    out = [data, (int(a.num_hidden), d)]
    if not a.no_bias:
        out.append((int(a.num_hidden),))
    return out


_get_op("FullyConnected").infer_args = _fc_infer


def _conv_infer(a, shapes):
    data = shapes[0]
    c = data[-1] if a.get("layout") == "NHWC" else data[1]
    w = (int(a.num_filter), c // int(a.num_group)) + tuple(a.kernel)
    out = [data, w]
    if not a.no_bias:
        out.append((int(a.num_filter),))
    return out


_get_op("Convolution").infer_args = _conv_infer


def _deconv_infer(a, shapes):
    data = shapes[0]
    c = data[1]
    w = (c, int(a.num_filter) // int(a.num_group)) + tuple(a.kernel)
    out = [data, w]
    if not a.no_bias:
        out.append((int(a.num_filter),))
    return out


_get_op("Deconvolution").infer_args = _deconv_infer


def _bn_infer(a, shapes):
    data = shapes[0]
    c = (data[int(a.get("axis", 1))],)
    return [data, c, c, c, c]


_get_op("BatchNorm").infer_args = _bn_infer


def _ln_infer(a, shapes):
    data = shapes[0]
    c = (data[int(a.get("axis", -1)) % len(data)],)
    return [data, c, c]


_get_op("LayerNorm").infer_args = _ln_infer


def _in_infer(a, shapes):
    data = shapes[0]
    c = (data[1],)
    return [data, c, c]


_get_op("InstanceNorm").infer_args = _in_infer


def _emb_infer(a, shapes):
    return [shapes[0], (int(a.input_dim), int(a.output_dim))]


_get_op("Embedding").infer_args = _emb_infer


def _prelu_infer(a, shapes):
    data = shapes[0]
    if a.act_type == "prelu":
        return [data, (data[1],)]
    return [data]


_get_op("LeakyReLU").infer_args = _prelu_infer


def _label_like_batch(a, shapes):
    data = shapes[0]
    if a.get("multi_output"):
        lbl = (data[0],) + tuple(data[2:])
    else:
        lbl = (data[0],)
    return [data, shapes[1] if shapes[1] is not None else lbl]


_get_op("SoftmaxOutput").infer_args = _label_like_batch
_get_op("SVMOutput").infer_args = _label_like_batch


def _label_like_data(a, shapes):
    return [shapes[0], shapes[1] if shapes[1] is not None else shapes[0]]


for _n in ("LinearRegressionOutput", "LogisticRegressionOutput",
           "MAERegressionOutput"):
    _get_op(_n).infer_args = _label_like_data
