"""Tensor op families: elemwise, broadcast, reduce, matrix/shape, indexing, init,
ordering, control flow, dot.

Parity with reference src/operator/tensor/* (SURVEY.md Appendix A census):
elemwise_unary_op.cc:32-901, elemwise_binary_op_*.cc, elemwise_binary_scalar_op_*.cc,
elemwise_binary_broadcast_op_*.cc, broadcast_reduce_op_{value,index}.cc, matrix_op.cc,
indexing_op.cc, init_op.cc, ordering_op.cc, control_flow_op.cc, dot.cc.
Each maps ~1:1 onto jnp/lax; gradients come from JAX autodiff instead of the
reference's hand-registered _backward_* ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .registry import Required, register

# ---------------------------------------------------------------- helpers


def _axis_tuple(axis, ndim, exclude=False):
    if axis is None or axis == () or axis == []:
        ax = tuple(range(ndim))
    elif isinstance(axis, int):
        ax = (axis % ndim,)
    else:
        ax = tuple(a % ndim for a in axis)
    if exclude:
        ax = tuple(i for i in range(ndim) if i not in ax)
    return ax


def unary(name, f, **kw):
    register(name, lambda a, x: f(x), arg_names=["data"], attrs={}, **kw)


def binary(name, f, **kw):
    register(name, lambda a, l, r: f(l, r), arg_names=["lhs", "rhs"], attrs={}, **kw)


def binary_scalar(name, f, **kw):
    register(name, lambda a, x: f(x, jnp.asarray(a.scalar, x.dtype)),
             arg_names=["data"], attrs={"scalar": Required(float)}, **kw)


def _logic(f):
    return lambda l, r: f(l, r).astype(l.dtype if hasattr(l, "dtype") else jnp.float32)


# ---------------------------------------------------------------- unary math
unary("relu", lambda x: jnp.maximum(x, 0))
unary("sigmoid", jax.nn.sigmoid)
unary("softsign", lambda x: x / (1 + jnp.abs(x)))
unary("_copy", lambda x: x)
unary("identity", lambda x: x)
unary("BlockGrad", lax.stop_gradient, aliases=("stop_gradient",))
unary("make_loss", lambda x: x)
unary("negative", lambda x: -x)
unary("reciprocal", lambda x: 1 / x)
unary("abs", jnp.abs)
unary("sign", jnp.sign)
unary("round", jnp.round)
unary("rint", jnp.rint)
unary("ceil", jnp.ceil)
unary("floor", jnp.floor)
unary("trunc", jnp.trunc)
unary("fix", jnp.trunc)
unary("square", jnp.square)
unary("sqrt", jnp.sqrt)
unary("rsqrt", lambda x: 1 / jnp.sqrt(x))
unary("cbrt", jnp.cbrt)
unary("rcbrt", lambda x: 1 / jnp.cbrt(x))
unary("exp", jnp.exp)
unary("log", jnp.log)
unary("log10", jnp.log10)
unary("log2", jnp.log2)
unary("log1p", jnp.log1p)
unary("expm1", jnp.expm1)
unary("sin", jnp.sin)
unary("cos", jnp.cos)
unary("tan", jnp.tan)
unary("arcsin", jnp.arcsin)
unary("arccos", jnp.arccos)
unary("arctan", jnp.arctan)
unary("degrees", jnp.degrees)
unary("radians", jnp.radians)
unary("sinh", jnp.sinh)
unary("cosh", jnp.cosh)
unary("tanh", jnp.tanh)
unary("arcsinh", jnp.arcsinh)
unary("arccosh", jnp.arccosh)
unary("arctanh", jnp.arctanh)
unary("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
unary("gammaln", jax.scipy.special.gammaln)
unary("erf", jax.scipy.special.erf)
unary("zeros_like", jnp.zeros_like)
unary("ones_like", jnp.ones_like)

register("Cast", lambda a, x: x.astype(_np.dtype(a.dtype)),
         attrs={"dtype": Required(str)}, aliases=("cast",))
register("_identity_with_attr_like_rhs", lambda a, l, r: l, arg_names=["lhs", "rhs"], attrs={})


# ------------------------------------------------- int8 PTQ casts (compile quant)
def _q8_scale(a, like):
    """The scale attr as a broadcastable f32 array: per-tensor when
    ``axis`` is negative (one scale for the whole tensor), per-channel
    along ``axis`` otherwise (one scale per slice, reshaped so it
    broadcasts against ``like``)."""
    s = jnp.asarray(tuple(a.scale), jnp.float32)
    axis = int(a.axis)
    if axis < 0 or like.ndim == 0:
        return s.reshape(()) if s.size == 1 else s
    shape = [1] * like.ndim
    shape[axis] = s.shape[0]
    return s.reshape(shape)


def _quantize_int8(a, x):
    q = jnp.round(x.astype(jnp.float32) / _q8_scale(a, x))
    return jnp.clip(q, -127, 127).astype(jnp.int8)


def _dequantize_int8(a, q):
    out = q.astype(jnp.float32) * _q8_scale(a, q)
    return out.astype(_np.dtype(a.out_dtype))


register("quantize_int8", _quantize_int8,
         attrs={"scale": Required(tuple), "axis": -1},
         doc="Symmetric int8 quantize: round(clip(x/scale, -127, 127)) "
             "as int8. scale is a tuple of f32 scales — one element for "
             "per-tensor (axis<0), one per slice of `axis` for "
             "per-channel. The inverse of dequantize_int8; inserted by "
             "the compile pipeline's `quant` pass, never user-authored.")
register("dequantize_int8", _dequantize_int8,
         attrs={"scale": Required(tuple), "axis": -1,
                "out_dtype": "float32"},
         doc="Symmetric int8 dequantize: q * scale, cast to out_dtype. "
             "scale/axis mirror quantize_int8; out_dtype lets the pair "
             "compose with the bf16 rewrite (bf16 activations round-"
             "trip through int8 without an extra Cast).")

# ---------------------------------------------------------------- binary elemwise
binary("elemwise_add", jnp.add, aliases=("_plus", "_add"))
binary("_grad_add", jnp.add)
binary("elemwise_sub", jnp.subtract, aliases=("_minus", "_sub"))
binary("elemwise_mul", jnp.multiply, aliases=("_mul",))
binary("elemwise_div", jnp.divide, aliases=("_div",))
binary("_mod", jnp.mod)
binary("_hypot", jnp.hypot)
binary("_maximum", jnp.maximum)
binary("_minimum", jnp.minimum)
binary("_power", jnp.power)
binary("_equal", _logic(jnp.equal))
binary("_not_equal", _logic(jnp.not_equal))
binary("_greater", _logic(jnp.greater))
binary("_greater_equal", _logic(jnp.greater_equal))
binary("_lesser", _logic(jnp.less))
binary("_lesser_equal", _logic(jnp.less_equal))

register("add_n", lambda a, *xs: sum(xs[1:], xs[0]), variadic="num_args",
         attrs={"num_args": Required(int)}, aliases=("ElementWiseSum", "_sum"))

# ---------------------------------------------------------------- scalar elemwise
binary_scalar("_plus_scalar", jnp.add)
binary_scalar("_minus_scalar", jnp.subtract)
binary_scalar("_rminus_scalar", lambda x, s: s - x)
binary_scalar("_mul_scalar", jnp.multiply)
binary_scalar("_div_scalar", jnp.divide)
binary_scalar("_rdiv_scalar", lambda x, s: s / x)
binary_scalar("_mod_scalar", jnp.mod)
binary_scalar("_rmod_scalar", lambda x, s: jnp.mod(s, x))
binary_scalar("_maximum_scalar", jnp.maximum)
binary_scalar("_minimum_scalar", jnp.minimum)
binary_scalar("_power_scalar", jnp.power)
binary_scalar("_rpower_scalar", lambda x, s: jnp.power(s, x))
binary_scalar("_hypot_scalar", jnp.hypot)
binary_scalar("_equal_scalar", _logic(jnp.equal))
binary_scalar("_not_equal_scalar", _logic(jnp.not_equal))
binary_scalar("_greater_scalar", _logic(jnp.greater))
binary_scalar("_greater_equal_scalar", _logic(jnp.greater_equal))
binary_scalar("_lesser_scalar", _logic(jnp.less))
binary_scalar("_lesser_equal_scalar", _logic(jnp.less_equal))

register("smooth_l1",
         lambda a, x: jnp.where(jnp.abs(x) < 1.0 / (a.scalar ** 2),
                                0.5 * (x * a.scalar) ** 2,
                                jnp.abs(x) - 0.5 / (a.scalar ** 2)),
         attrs={"scalar": 1.0})

# ---------------------------------------------------------------- broadcast binary
for _n, _f in [("add", jnp.add), ("plus", jnp.add), ("sub", jnp.subtract),
               ("minus", jnp.subtract), ("mul", jnp.multiply), ("div", jnp.divide),
               ("mod", jnp.mod), ("power", jnp.power), ("maximum", jnp.maximum),
               ("minimum", jnp.minimum), ("hypot", jnp.hypot),
               ("equal", _logic(jnp.equal)), ("not_equal", _logic(jnp.not_equal)),
               ("greater", _logic(jnp.greater)), ("greater_equal", _logic(jnp.greater_equal)),
               ("lesser", _logic(jnp.less)), ("lesser_equal", _logic(jnp.less_equal))]:
    binary("broadcast_" + _n, _f)

register("broadcast_axis",
         lambda a, x: jnp.broadcast_to(
             x, tuple(a.size[list(_axis_tuple(a.axis, x.ndim)).index(i)]
                      if i in _axis_tuple(a.axis, x.ndim) else x.shape[i]
                      for i in range(x.ndim))),
         attrs={"axis": (), "size": ()}, aliases=("broadcast_axes",))
register("broadcast_to",
         lambda a, x: jnp.broadcast_to(
             x, tuple(s if s != 0 else x.shape[i] for i, s in enumerate(a.shape))),
         attrs={"shape": Required(tuple)})

# ---------------------------------------------------------------- reductions


def _reduce(name, f, default_all=True):
    def impl(a, x):
        ax = _axis_tuple(a.axis, x.ndim, a.exclude)
        return f(x, axis=ax, keepdims=bool(a.keepdims))

    register(name, impl, attrs={"axis": None, "keepdims": False, "exclude": False})


_reduce("sum", jnp.sum)
_reduce("mean", jnp.mean)
_reduce("prod", jnp.prod)
_reduce("nansum", jnp.nansum)
_reduce("nanprod", jnp.nanprod)
_reduce("max", jnp.max)
_reduce("min", jnp.min)
register("sum_axis", lambda a, x: jnp.sum(x, axis=_axis_tuple(a.axis, x.ndim, a.exclude),
                                          keepdims=bool(a.keepdims)),
         attrs={"axis": None, "keepdims": False, "exclude": False})

register("norm", lambda a, x: jnp.sqrt(jnp.sum(jnp.square(x))), attrs={})


def _arg_reduce(name, f):
    def impl(a, x):
        if a.axis is None:
            r = f(jnp.ravel(x), axis=0)
            return r.astype(x.dtype) if not a.keepdims else jnp.reshape(
                r, (1,) * x.ndim).astype(x.dtype)
        r = f(x, axis=int(a.axis))
        if a.keepdims:
            r = jnp.expand_dims(r, int(a.axis))
        return r.astype(x.dtype)

    register(name, impl, attrs={"axis": None, "keepdims": False})


_arg_reduce("argmax", jnp.argmax)
_arg_reduce("argmin", jnp.argmin)
register("argmax_channel", lambda a, x: jnp.argmax(x, axis=1).astype(x.dtype), attrs={})


def _pick(a, x, index):
    axis = int(a.axis) if a.axis is not None else -1
    idx = index.astype(jnp.int32)
    picked = jnp.take_along_axis(x, jnp.expand_dims(idx, axis % x.ndim), axis=axis)
    if not a.keepdims:
        picked = jnp.squeeze(picked, axis=axis % x.ndim)
    return picked


register("pick", _pick, arg_names=["data", "index"],
         attrs={"axis": -1, "keepdims": False})

# ---------------------------------------------------------------- matrix / shape


def _infer_reshape(shape_spec, in_shape):
    """MXNet reshape mini-language: 0 copy, -1 infer, -2 rest, -3 merge, -4 split."""
    out = []
    src = list(in_shape)
    i = 0  # index into src
    j = 0
    spec = list(shape_spec)
    while j < len(spec):
        s = spec[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a1, a2 = spec[j + 1], spec[j + 2]
            if a1 == -1:
                a1 = src[i] // a2
            if a2 == -1:
                a2 = src[i] // a1
            out.extend([a1, a2]); i += 1; j += 2
        else:
            out.append(int(s))
            if i < len(src):
                i += 1
        j += 1
    if -1 in out:
        known = 1
        for v in out:
            if v != -1:
                known *= v
        total = 1
        for v in in_shape:
            total *= v
        out[out.index(-1)] = total // max(known, 1)
    return tuple(out)


def _reshape(a, x):
    shape = a.shape
    if not shape and a.target_shape:
        # legacy target_shape (reference matrix_op.cc Reshape: deprecated
        # but accepted; a 0 dim is inferred from the remaining dims;
        # keep_highest=True ignores the first target dim and keeps the
        # input's leading dim, matrix_op-inl.h)
        tgt = tuple(a.target_shape)
        if a.keep_highest:
            tgt = (x.shape[0],) + tgt[1:]
        shape = tuple(-1 if d == 0 else d for d in tgt)
    if not shape:
        raise MXNetError("Reshape requires shape= (or legacy target_shape=)")
    if a.reverse:
        rev = _infer_reshape(tuple(reversed(shape)), tuple(reversed(x.shape)))
        return jnp.reshape(x, tuple(reversed(rev)))
    return jnp.reshape(x, _infer_reshape(shape, x.shape))


register("Reshape", _reshape,
         attrs={"shape": (), "target_shape": (), "reverse": False,
                "keep_highest": False},
         aliases=("reshape",))
register("Flatten", lambda a, x: jnp.reshape(x, (x.shape[0], -1)), attrs={},
         aliases=("flatten",))
register("reshape_like", lambda a, l, r: jnp.reshape(l, r.shape),
         arg_names=["lhs", "rhs"], attrs={})
register("transpose", lambda a, x: jnp.transpose(x, a.axes if a.axes else None),
         attrs={"axes": ()})
register("expand_dims", lambda a, x: jnp.expand_dims(x, int(a.axis)),
         attrs={"axis": Required(int)})
register("SwapAxis", lambda a, x: jnp.swapaxes(x, int(a.dim1), int(a.dim2)),
         attrs={"dim1": 0, "dim2": 0}, aliases=("swapaxes",))


def _slice(a, x):
    begin = list(a.begin)
    end = list(a.end)
    idx = []
    for d in range(x.ndim):
        b = begin[d] if d < len(begin) and begin[d] is not None else 0
        e = end[d] if d < len(end) and end[d] is not None else x.shape[d]
        if b < 0:
            b += x.shape[d]
        if e < 0:
            e += x.shape[d]
        idx.append(slice(b, e))
    return x[tuple(idx)]


register("slice", _slice, attrs={"begin": Required(tuple), "end": Required(tuple)},
         aliases=("crop",))


def _slice_idx(a, shape):
    begin = list(a.begin)
    end = list(a.end)
    idx = []
    for d in range(len(shape)):
        b = begin[d] if d < len(begin) and begin[d] is not None else 0
        e = end[d] if d < len(end) and end[d] is not None else shape[d]
        if b < 0:
            b += shape[d]
        if e < 0:
            e += shape[d]
        idx.append(slice(b, e))
    return tuple(idx)


def _slice_assign(a, lhs, rhs):
    """Functional slice assignment (reference matrix_op.cc _slice_assign /
    _crop_assign): returns lhs with lhs[begin:end] = rhs."""
    return lhs.at[_slice_idx(a, lhs.shape)].set(rhs.astype(lhs.dtype))


register("_slice_assign", _slice_assign, arg_names=["lhs", "rhs"],
         attrs={"begin": Required(tuple), "end": Required(tuple)},
         aliases=("_crop_assign",))


def _slice_assign_scalar(a, data):
    """lhs[begin:end] = scalar (reference _crop_assign_scalar)."""
    return data.at[_slice_idx(a, data.shape)].set(
        jnp.asarray(a.scalar, data.dtype))


register("_slice_assign_scalar", _slice_assign_scalar,
         attrs={"begin": Required(tuple), "end": Required(tuple),
                "scalar": 0.0},
         aliases=("_crop_assign_scalar",))


def _slice_axis(a, x):
    ax = int(a.axis) % x.ndim
    b = a.begin or 0
    e = a.end if a.end is not None else x.shape[ax]
    if b < 0:
        b += x.shape[ax]
    if e < 0:
        e += x.shape[ax]
    idx = [slice(None)] * x.ndim
    idx[ax] = slice(b, e)
    return x[tuple(idx)]


register("slice_axis", _slice_axis,
         attrs={"axis": Required(int), "begin": 0, "end": None})

register("clip", lambda a, x: jnp.clip(x, a.a_min, a.a_max),
         attrs={"a_min": Required(float), "a_max": Required(float)})
register("repeat",
         lambda a, x: jnp.repeat(x, int(a.repeats),
                                 axis=None if a.axis is None else int(a.axis)),
         attrs={"repeats": Required(int), "axis": None})
register("tile", lambda a, x: jnp.tile(x, a.reps), attrs={"reps": Required(tuple)})
register("reverse", lambda a, x: jnp.flip(x, axis=tuple(int(i) for i in a.axis)),
         attrs={"axis": Required(tuple)}, aliases=("flip",))
register("stack", lambda a, *xs: jnp.stack(xs, axis=int(a.axis)),
         variadic="num_args", attrs={"num_args": Required(int), "axis": 0})
register("space_to_depth", lambda a, x: lax.reshape(
    jnp.transpose(jnp.reshape(x, (x.shape[0], x.shape[1], x.shape[2] // a.block_size,
                                  a.block_size, x.shape[3] // a.block_size, a.block_size)),
                  (0, 3, 5, 1, 2, 4)),
    (x.shape[0], x.shape[1] * a.block_size ** 2,
     x.shape[2] // a.block_size, x.shape[3] // a.block_size)),
    attrs={"block_size": Required(int)})

# ---------------------------------------------------------------- dot


def _dot(a, lhs, rhs):
    l = jnp.swapaxes(lhs, 0, 1) if a.transpose_a and lhs.ndim == 2 else lhs
    r = jnp.swapaxes(rhs, 0, 1) if a.transpose_b and rhs.ndim == 2 else rhs
    if a.transpose_a and lhs.ndim > 2:
        l = jnp.transpose(lhs, tuple(range(1, lhs.ndim)) + (0,))
    if a.transpose_b and rhs.ndim > 2:
        r = jnp.transpose(rhs, (rhs.ndim - 1,) + tuple(range(rhs.ndim - 1)))
    return jnp.dot(l, r)


register("dot", _dot, arg_names=["lhs", "rhs"],
         attrs={"transpose_a": False, "transpose_b": False})


def _batch_dot(a, lhs, rhs):
    l = jnp.swapaxes(lhs, -1, -2) if a.transpose_a else lhs
    r = jnp.swapaxes(rhs, -1, -2) if a.transpose_b else rhs
    return jnp.matmul(l, r)


register("batch_dot", _batch_dot, arg_names=["lhs", "rhs"],
         attrs={"transpose_a": False, "transpose_b": False})

# ---------------------------------------------------------------- indexing


def _embedding(a, data, weight):
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0)


register("Embedding", _embedding, arg_names=["data", "weight"],
         attrs={"input_dim": Required(int), "output_dim": Required(int),
                "dtype": "float32"})


def _take(a, data, indices):
    mode = {"clip": "clip", "wrap": "wrap"}.get(a.mode, "clip")
    return jnp.take(data, indices.astype(jnp.int32), axis=int(a.axis), mode=mode)


register("take", _take, arg_names=["a", "indices"], attrs={"axis": 0, "mode": "clip"})

register("batch_take",
         lambda a, x, idx: jnp.take_along_axis(
             x, idx.astype(jnp.int32)[:, None], axis=1)[:, 0],
         arg_names=["a", "indices"], attrs={})


def _one_hot(a, idx):
    out = jax.nn.one_hot(idx.astype(jnp.int32), int(a.depth),
                         dtype=_np.dtype(a.dtype))
    return out * (a.on_value - a.off_value) + a.off_value


register("one_hot", _one_hot,
         attrs={"depth": Required(int), "on_value": 1.0, "off_value": 0.0,
                "dtype": "float32"})


def _gather_nd(a, data, indices):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


register("gather_nd", _gather_nd, arg_names=["data", "indices"], attrs={})


def _scatter_nd(a, data, indices):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(tuple(a.shape), dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].add(data)


register("scatter_nd", _scatter_nd, arg_names=["data", "indices"],
         attrs={"shape": Required(tuple)})

# ---------------------------------------------------------------- init ops


def _full(a, value):
    dtype = _np.dtype(a.dtype if a.dtype else "float32")
    return jnp.full(tuple(a.shape), value, dtype=dtype)


register("_zeros", lambda a: _full(a, 0), arg_names=[],
         attrs={"shape": Required(tuple), "dtype": "float32", "ctx": ""})
register("_ones", lambda a: _full(a, 1), arg_names=[],
         attrs={"shape": Required(tuple), "dtype": "float32", "ctx": ""})
register("_full", lambda a: _full(a, a.value), arg_names=[],
         attrs={"shape": Required(tuple), "dtype": "float32", "ctx": "",
                "value": Required(float)})
def _arange(a):
    start, stop = a.start, a.stop
    if stop is None:
        start, stop = 0.0, start
    base = jnp.arange(start, stop, a.step, dtype=_np.dtype(a.dtype))
    return jnp.repeat(base, int(a.repeat)) if int(a.repeat) > 1 else base


register("_arange", _arange, arg_names=[],
         attrs={"start": 0.0, "stop": None, "step": 1.0, "repeat": 1,
                "dtype": "float32", "ctx": ""})

# ---------------------------------------------------------------- ordering


def _topk(a, x):
    axis = x.ndim - 1 if a.axis is None else int(a.axis) % x.ndim
    k = int(a.k) if int(a.k) > 0 else x.shape[axis]
    xm = jnp.moveaxis(x, axis, -1)
    vals = -jnp.sort(-xm, axis=-1) if not a.is_ascend else jnp.sort(xm, axis=-1)
    idxs = jnp.argsort(-xm if not a.is_ascend else xm, axis=-1, stable=True)
    vals, idxs = vals[..., :k], idxs[..., :k]
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis)
    rt = a.ret_typ
    if rt == "value":
        return vals
    if rt == "indices":
        return idxs.astype(x.dtype)
    if rt == "mask":
        m = jnp.zeros(xm.shape, dtype=x.dtype)
        m = m.at[..., :1].set(0)  # placeholder to keep shape
        onehot = jax.nn.one_hot(idxs.reshape(idxs.shape), xm.shape[-1], dtype=x.dtype)
        mask = jnp.moveaxis(jnp.sum(onehot, axis=-2), -1, axis)
        return mask
    return vals, idxs.astype(x.dtype)


register("topk", _topk,
         attrs={"axis": -1, "k": 1, "ret_typ": "indices", "is_ascend": False},
         num_outputs=lambda a: 2 if a.ret_typ == "both" else 1)


def _sort(a, x):
    axis = x.ndim - 1 if a.axis is None else int(a.axis) % x.ndim
    s = jnp.sort(x, axis=axis)
    return s if a.is_ascend else jnp.flip(s, axis=axis)


register("sort", _sort, attrs={"axis": -1, "is_ascend": True})


def _argsort(a, x):
    axis = x.ndim - 1 if a.axis is None else int(a.axis) % x.ndim
    idx = jnp.argsort(x if a.is_ascend else -x, axis=axis, stable=True)
    return idx.astype(x.dtype)


register("argsort", _argsort, attrs={"axis": -1, "is_ascend": True})

# ---------------------------------------------------------------- control flow
register("where", lambda a, c, l, r: jnp.where(c.astype(bool), l, r),
         arg_names=["condition", "x", "y"], attrs={})

# ---------------------------------------------------------------- sparse-compat
register("cast_storage", lambda a, x: x, attrs={"stype": Required(str)})
register("_square_sum",
         lambda a, x: jnp.sum(jnp.square(x),
                              axis=_axis_tuple(a.axis, x.ndim),
                              keepdims=bool(a.keepdims)),
         attrs={"axis": None, "keepdims": False})
