"""Pallas epilogue kernel: BN-apply + ReLU + residual-add in ONE pass over
the activation (VERDICT r3 next #2 — test whether a hand-fused epilogue
beats XLA's own elementwise fusion on the bytes the ResNet train step
moves between a conv output and the next conv input).

The BN *apply* stage is an affine per-channel transform (scale/shift
folded from batch stats, gamma, beta — batch_norm-inl.h's normalize step);
fusing it with the activation and the block-join add means the conv
output is read ONCE and the block input written ONCE. XLA usually builds
the same fusion by itself — `tools/bench_epilogue.py` measures whether
there is anything left on the table (the answer feeds docs/perf.md).

Layout: channel-minor (M, C) tiles, the TPU-native layout (C is the
128-lane axis). NCHW callers reshape/transpose outside; the microbench
works directly in (N*H*W, C).
"""
import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover - pallas always present in this env
    _HAVE_PALLAS = False


def _kernel(x_ref, s_ref, b_ref, r_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    y = x * s_ref[...] + b_ref[...]
    y = jnp.maximum(y, 0.0)
    if r_ref is not None:
        y = y + r_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def bn_apply_relu_add(x, scale, shift, residual=None, block_m=1024,
                      interpret=False):
    """y = relu(x * scale + shift) [+ residual], one HBM pass.

    x (M, C) bf16/f32; scale/shift (C,) f32; residual optional (M, C).
    """
    m, c = x.shape
    block_m = min(block_m, m)
    grid = (pl.cdiv(m, block_m),)
    scale2 = scale.reshape(1, c).astype(jnp.float32)
    shift2 = shift.reshape(1, c).astype(jnp.float32)

    in_specs = [
        pl.BlockSpec((block_m, c), lambda i: (i, 0)),
        pl.BlockSpec((1, c), lambda i: (0, 0)),
        pl.BlockSpec((1, c), lambda i: (0, 0)),
    ]
    args = [x, scale2, shift2]
    if residual is not None:
        in_specs.append(pl.BlockSpec((block_m, c), lambda i: (i, 0)))
        args.append(residual)
        kern = _kernel
    else:
        def kern(x_ref, s_ref, b_ref, o_ref):
            return _kernel(x_ref, s_ref, b_ref, None, o_ref)

    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((m, c), x.dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_m, c), lambda i: (i, 0)),
        interpret=interpret,
    )(*args)


def bn_apply_relu_add_reference(x, scale, shift, residual=None):
    """The XLA-fused formulation the kernel competes with."""
    y = x.astype(jnp.float32) * scale.astype(jnp.float32) \
        + shift.astype(jnp.float32)
    y = jnp.maximum(y, 0.0)
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    return y.astype(x.dtype)


def fold_bn(gamma, beta, mean, var, eps=1e-5):
    """Fold BN statistics into the per-channel (scale, shift) the apply
    stage consumes: scale = gamma*rsqrt(var+eps), shift = beta-mean*scale
    (batch_norm-inl.h normalize step)."""
    scale = gamma * jax.lax.rsqrt(var + eps)
    return scale, beta - mean * scale
