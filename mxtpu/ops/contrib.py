"""Contrib operators: SSD multibox trio + NMS, quantization, fft, count_sketch.

TPU-native equivalents of src/operator/contrib/ (multibox_prior.cc,
multibox_target.cc, multibox_detection.cc, quantize.cc, dequantize.cc,
fft.cc, count_sketch.cc). The reference implements these as hand-written
CPU/CUDA kernels; here each is expressed over jax arrays with static shapes —
anchor generation is pure broadcasting, target matching is an IOU matrix +
argmax/sort, and NMS is a sequential suppression scan (lax.scan) over
score-sorted candidates, all of which XLA fuses into a few kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .registry import Required, register

# ------------------------------------------------------------------ box utils


def _box_iou_corner(a, b):
    """IOU between two corner-format box sets: a (A,4), b (B,4) -> (A,B)."""
    ax1, ay1, ax2, ay2 = jnp.split(a, 4, axis=-1)  # (A,1)
    bx1, by1, bx2, by2 = [v[:, 0] for v in jnp.split(b, 4, axis=-1)]  # (B,)
    ix1 = jnp.maximum(ax1, bx1)
    iy1 = jnp.maximum(ay1, by1)
    ix2 = jnp.minimum(ax2, bx2)
    iy2 = jnp.minimum(ay2, by2)
    iw = jnp.maximum(ix2 - ix1, 0)
    ih = jnp.maximum(iy2 - iy1, 0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0) * jnp.maximum(ay2 - ay1, 0)
    area_b = jnp.maximum(bx2 - bx1, 0) * jnp.maximum(by2 - by1, 0)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / union, 0.0)


# ------------------------------------------------------------ MultiBoxPrior


def _multibox_prior(a, data):
    """Generate SSD anchor boxes for one feature map.

    data: (N, C, H, W). Output (1, H*W*num_anchors, 4) corner boxes in
    [0,1] coords; num_anchors = len(sizes) + len(ratios) - 1
    (reference src/operator/contrib/multibox_prior-inl.h).
    """
    _, _, H, W = data.shape
    sizes = [float(s) for s in a.sizes]
    ratios = [float(r) for r in a.ratios]
    steps = a.steps
    offsets = a.offsets
    step_y = float(steps[0]) if steps and float(steps[0]) > 0 else 1.0 / H
    step_x = float(steps[1]) if steps and float(steps[1]) > 0 else 1.0 / W
    off_y, off_x = float(offsets[0]), float(offsets[1])

    cy = (jnp.arange(H, dtype=jnp.float32) + off_y) * step_y  # (H,)
    cx = (jnp.arange(W, dtype=jnp.float32) + off_x) * step_x  # (W,)
    cxg, cyg = jnp.meshgrid(cx, cy)  # (H,W)

    wh = []
    for s in sizes:  # (size_i, ratios[0])
        r = ratios[0]
        wh.append((s * _np.sqrt(r) / 2, s / _np.sqrt(r) / 2))
    for r in ratios[1:]:  # (sizes[0], ratio_j)
        wh.append((sizes[0] * _np.sqrt(r) / 2, sizes[0] / _np.sqrt(r) / 2))
    wh = jnp.asarray(wh, dtype=jnp.float32)  # (K, 2) half-extents

    cxg = cxg[..., None]  # (H,W,1)
    cyg = cyg[..., None]
    hw_, hh_ = wh[:, 0], wh[:, 1]  # (K,)
    boxes = jnp.stack([cxg - hw_, cyg - hh_, cxg + hw_, cyg + hh_],
                      axis=-1)  # (H,W,K,4)
    boxes = boxes.reshape(1, -1, 4)
    if a.clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


register("_contrib_MultiBoxPrior", _multibox_prior,
         attrs={"sizes": (1.0,), "ratios": (1.0,), "clip": False,
                "steps": (-1.0, -1.0), "offsets": (0.5, 0.5)},
         aliases=("MultiBoxPrior",))


# ------------------------------------------------------------ MultiBoxTarget


def _encode_loc(anchors, gt, variances):
    """Corner anchors (A,4) + matched GT corners (A,4) -> loc targets (A,4)."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-8)
    gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-8)
    gcx = (gt[:, 0] + gt[:, 2]) / 2
    gcy = (gt[:, 1] + gt[:, 3]) / 2
    v0, v1, v2, v3 = [float(v) for v in variances]
    tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / v0
    ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / v1
    tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / v2
    th = jnp.log(gh / jnp.maximum(ah, 1e-8)) / v3
    return jnp.stack([tx, ty, tw, th], axis=-1)


def _multibox_target_one(anchors, label, cls_pred, a):
    """One sample. anchors (A,4), label (G,5) [cls,x1,y1,x2,y2] (cls=-1 pad),
    cls_pred (num_cls+1, A). Returns loc_target (A*4,), loc_mask (A*4,),
    cls_target (A,)."""
    A = anchors.shape[0]
    valid_gt = label[:, 0] >= 0  # (G,)
    iou = _box_iou_corner(anchors, label[:, 1:5])  # (A,G)
    iou = jnp.where(valid_gt[None, :], iou, -1.0)

    # step 1: each valid GT claims its best anchor (bipartite-greedy in the
    # reference; here one-shot argmax per GT — ties/conflicts resolved by
    # later GT winning, which matches the reference for disjoint objects)
    best_anchor_per_gt = jnp.argmax(iou, axis=0)  # (G,)
    # step 2: each anchor takes its best GT if IOU > threshold
    best_gt_per_anchor = jnp.argmax(iou, axis=1)  # (A,)
    best_iou_per_anchor = jnp.max(iou, axis=1)  # (A,)
    matched = best_iou_per_anchor > float(a.overlap_threshold)  # (A,)
    match_gt = best_gt_per_anchor

    # force-match the per-GT best anchors
    G = label.shape[0]
    forced = jnp.zeros((A,), dtype=bool)
    forced_gt = jnp.zeros((A,), dtype=jnp.int32)

    def body(g, carry):
        forced, forced_gt = carry
        anc = best_anchor_per_gt[g]
        use = valid_gt[g]
        forced = forced.at[anc].set(jnp.where(use, True, forced[anc]))
        forced_gt = forced_gt.at[anc].set(
            jnp.where(use, g, forced_gt[anc]).astype(jnp.int32))
        return forced, forced_gt

    forced, forced_gt = lax.fori_loop(0, G, body, (forced, forced_gt))
    matched = matched | forced
    match_gt = jnp.where(forced, forced_gt, match_gt)

    gt_cls = label[:, 0].astype(jnp.int32)  # (G,)
    cls_target = jnp.where(matched, gt_cls[match_gt] + 1, 0)  # 0 = background

    # negative mining: keep top (ratio * num_pos) negatives by max non-bg
    # score, mark the rest ignore_label
    ratio = float(a.negative_mining_ratio)
    if ratio > 0:
        num_pos = jnp.sum(matched)
        max_neg = jnp.maximum(ratio * num_pos,
                              int(a.minimum_negative_samples))
        # hardness score = max non-background prediction (multibox_target.cc);
        # anchors overlapping a GT above negative_mining_thresh are excluded
        # from the negative pool even though they fell short of
        # overlap_threshold (multibox_target.cc:215)
        neg_score = jnp.max(cls_pred[1:, :], axis=0)  # (A,)
        ineligible = matched | (best_iou_per_anchor >=
                                float(a.negative_mining_thresh))
        neg_score = jnp.where(ineligible, -jnp.inf, neg_score)
        order = jnp.argsort(-neg_score)  # hardest first
        rank = jnp.zeros((A,), jnp.int32).at[order].set(jnp.arange(A,
                                                        dtype=jnp.int32))
        keep_neg = (~matched) & (rank < max_neg)
        ignore = (~matched) & (~keep_neg)
        cls_target = jnp.where(ignore, int(a.ignore_label), cls_target)

    gt_boxes = label[:, 1:5][match_gt]  # (A,4)
    loc_t = _encode_loc(anchors, gt_boxes, a.variances)  # (A,4)
    loc_t = jnp.where(matched[:, None], loc_t, 0.0)
    loc_mask = jnp.where(matched[:, None],
                         jnp.ones_like(loc_t), jnp.zeros_like(loc_t))
    return (loc_t.reshape(-1), loc_mask.reshape(-1),
            cls_target.astype(anchors.dtype))


def _multibox_target(a, anchor, label, cls_pred):
    anchors = anchor[0]  # (A,4)
    loc_t, loc_m, cls_t = jax.vmap(
        lambda lb, cp: _multibox_target_one(anchors, lb, cp, a))(label,
                                                                 cls_pred)
    return loc_t, loc_m, cls_t


register("_contrib_MultiBoxTarget", _multibox_target,
         arg_names=["anchor", "label", "cls_pred"],
         attrs={"overlap_threshold": 0.5, "ignore_label": -1.0,
                "negative_mining_ratio": -1.0,
                "negative_mining_thresh": 0.5,
                "minimum_negative_samples": 0,
                "variances": (0.1, 0.1, 0.2, 0.2)},
         num_outputs=3, aliases=("MultiBoxTarget",))


# --------------------------------------------------------- MultiBoxDetection


def _decode_loc(anchors, loc, variances):
    v0, v1, v2, v3 = [float(v) for v in variances]
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    cx = loc[:, 0] * v0 * aw + acx
    cy = loc[:, 1] * v1 * ah + acy
    w = jnp.exp(loc[:, 2] * v2) * aw / 2
    h = jnp.exp(loc[:, 3] * v3) * ah / 2
    return jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=-1)


def _nms_scan(boxes, scores, cls_id, thresh, force_suppress):
    """Sequential NMS over score-sorted candidates via lax.scan.

    Returns keep mask aligned with the (sorted) input order.
    """
    K = boxes.shape[0]
    iou = _box_iou_corner(boxes, boxes)  # (K,K)
    same_cls = (cls_id[:, None] == cls_id[None, :]) | force_suppress
    suppress_pair = (iou > thresh) & same_cls  # j suppressed by i

    def step(alive, i):
        # candidate i survives iff still alive; if it survives it kills
        # its overlapping lower-scored neighbours
        keep_i = alive[i]
        alive = alive & ~(suppress_pair[i] & keep_i &
                          (jnp.arange(K) > i))
        return alive, keep_i

    alive0 = scores > -jnp.inf
    _, keep = lax.scan(step, alive0, jnp.arange(K))
    return keep


def _multibox_detection_one(cls_prob, loc_pred, anchors, a):
    """cls_prob (num_cls+1, A), loc_pred (A*4,), anchors (A,4) ->
    (A, 6) rows [cls_id, score, x1, y1, x2, y2], invalid rows cls_id=-1."""
    A = anchors.shape[0]
    boxes = _decode_loc(anchors, loc_pred.reshape(A, 4), a.variances)
    if a.clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    # per-anchor best foreground class
    fg = cls_prob[1:, :]  # (C, A)
    cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)  # (A,)
    score = jnp.max(fg, axis=0)
    valid = score > float(a.threshold)
    score = jnp.where(valid, score, -jnp.inf)

    order = jnp.argsort(-score)
    nms_topk = int(a.nms_topk)
    if nms_topk > 0 and nms_topk < A:
        order = order[:nms_topk]
    sb = boxes[order]
    ss = score[order]
    sc = cls_id[order]
    keep = _nms_scan(sb, ss, sc, float(a.nms_threshold),
                     bool(a.force_suppress))
    out_cls = jnp.where(keep & (ss > -jnp.inf), sc, -1.0)
    out_score = jnp.where(keep, ss, 0.0)
    out_score = jnp.where(jnp.isfinite(out_score), out_score, 0.0)
    out = jnp.concatenate([out_cls[:, None], out_score[:, None], sb],
                          axis=-1)
    if out.shape[0] < A:  # pad back to A rows
        pad = jnp.full((A - out.shape[0], 6), -1.0, out.dtype)
        pad = pad.at[:, 1:].set(0.0)
        out = jnp.concatenate([out, pad], axis=0)
    return out


def _multibox_detection(a, cls_prob, loc_pred, anchor):
    anchors = anchor[0]
    return jax.vmap(
        lambda cp, lp: _multibox_detection_one(cp, lp, anchors, a))(
            cls_prob, loc_pred)


register("_contrib_MultiBoxDetection", _multibox_detection,
         arg_names=["cls_prob", "loc_pred", "anchor"],
         attrs={"clip": True, "threshold": 0.01, "background_id": 0,
                "nms_threshold": 0.5, "force_suppress": False,
                "variances": (0.1, 0.1, 0.2, 0.2), "nms_topk": -1},
         aliases=("MultiBoxDetection",))


def _mbt_infer(a, shapes):
    return shapes


def _mbd_infer(a, shapes):
    return shapes


# ------------------------------------------------------------- quantization


def _quantize(a, data, min_range, max_range):
    """float -> uint8 affine quantization (contrib/quantize.cc)."""
    mn = min_range.reshape(())
    mx = max_range.reshape(())
    scale = 255.0 / jnp.maximum(mx - mn, 1e-8)
    q = jnp.clip(jnp.round((data - mn) * scale), 0, 255).astype(jnp.uint8)
    return q, mn.reshape(1), mx.reshape(1)


register("_contrib_quantize", _quantize,
         arg_names=["data", "min_range", "max_range"],
         attrs={"out_type": "uint8"}, num_outputs=3)


def _dequantize(a, data, min_range, max_range):
    mn = min_range.reshape(())
    mx = max_range.reshape(())
    scale = jnp.maximum(mx - mn, 1e-8) / 255.0
    return data.astype(jnp.float32) * scale + mn


register("_contrib_dequantize", _dequantize,
         arg_names=["data", "min_range", "max_range"],
         attrs={"out_type": "float32"})


# ---------------------------------------------------------------------- fft


def _fft(a, data):
    """Real->complex FFT packed as interleaved re/im on the last axis
    (contrib/fft.cc semantics: output last dim = 2*input last dim)."""
    f = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(jnp.float32)


register("_contrib_fft", _fft, attrs={"compute_size": 128})


def _ifft(a, data):
    """Interleaved re/im -> real inverse FFT (contrib/ifft.cc)."""
    n = data.shape[-1] // 2
    c = data.reshape(data.shape[:-1] + (n, 2))
    comp = c[..., 0] + 1j * c[..., 1]
    out = jnp.fft.ifft(comp, axis=-1)
    # reference returns unnormalized ifft * n; jnp.fft.ifft normalizes by n
    return (out.real * n).astype(jnp.float32)


register("_contrib_ifft", _ifft, attrs={"compute_size": 128})


# ------------------------------------------------------------------ CTCLoss


def _ctc_loss_one(logits, label, a, data_len=None, label_len=None):
    """CTC negative log-likelihood for one sequence.

    logits (T, C); label (L,) int labels. MXNet conventions
    (contrib/ctc_loss-inl.h): with blank_label='first' the blank is class 0
    and label value 0 means padding; with 'last' the blank is class C-1 and
    negative labels are padding. Log-domain alpha recursion over the
    expanded label [blank, l1, blank, l2, ..., blank] via lax.scan; when
    data_len is given, steps t >= data_len freeze the recursion.
    """
    T, C = logits.shape
    L = label.shape[0]
    logp = jax.nn.log_softmax(logits, axis=-1)
    lab = label.astype(jnp.int32)
    blank_first = str(a.blank_label) != "last"
    blank = 0 if blank_first else C - 1
    if label_len is not None:
        valid = jnp.arange(L) < label_len.astype(jnp.int32)
    elif blank_first:
        valid = lab > 0
    else:
        valid = lab >= 0
    n_lab = jnp.sum(valid.astype(jnp.int32))
    # compact the labels to the front (padding may be interleaved in theory)
    order = jnp.argsort(~valid, stable=True)
    lab = lab[order]
    S = 2 * L + 1
    ext = jnp.full((S,), blank, jnp.int32)
    ext = ext.at[1::2].set(jnp.clip(lab, 0, C - 1))  # labels at odd slots
    NEG = jnp.asarray(-1e30, logp.dtype)
    s_idx = jnp.arange(S)
    s_valid = s_idx < 2 * n_lab + 1
    # allow the skip transition a[s-2] when ext[s] != blank and != ext[s-2]
    ext_m2 = jnp.concatenate([jnp.full((2,), blank, jnp.int32), ext[:-2]])
    can_skip = (ext != blank) & (ext != ext_m2) & (s_idx >= 2)

    alpha0 = jnp.full((S,), NEG)
    alpha0 = alpha0.at[0].set(logp[0, blank])
    alpha0 = alpha0.at[1].set(jnp.where(n_lab > 0, logp[0, ext[1]], NEG))

    def step(alpha, xs):
        t, lp = xs
        a_prev = alpha
        a_m1 = jnp.concatenate([jnp.array([NEG]), alpha[:-1]])
        a_m2 = jnp.concatenate([jnp.full((2,), NEG), alpha[:-2]])
        a_m2 = jnp.where(can_skip, a_m2, NEG)
        m = jnp.maximum(jnp.maximum(a_prev, a_m1), a_m2)
        tot = m + jnp.log(jnp.exp(a_prev - m) + jnp.exp(a_m1 - m) +
                          jnp.exp(jnp.where(can_skip, a_m2, NEG) - m))
        tot = jnp.where(jnp.isfinite(m), tot, NEG)
        new = jnp.where(s_valid, tot + lp[ext], NEG)
        if data_len is not None:  # freeze past the true sequence end
            new = jnp.where(t < data_len.astype(jnp.int32), new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0,
                        (jnp.arange(1, T), logp[1:]))
    end1 = alpha[2 * n_lab]  # final blank
    end2 = jnp.where(n_lab > 0, alpha[2 * n_lab - 1], NEG)
    m = jnp.maximum(end1, end2)
    ll = m + jnp.log(jnp.exp(end1 - m) + jnp.exp(end2 - m))
    return -ll


def _ctc_loss(a, *inputs):
    """data (T, N, C) activations, label (N, L) -> loss (N,) (grad flows
    through data via jax.grad of this expression, replacing the reference's
    hand-written warp-ctc backward). Optional inputs follow arg order:
    data_lengths if use_data_lengths, then label_lengths if
    use_label_lengths."""
    data, label = inputs[0], inputs[1]
    i = 2
    data_lengths = label_lengths = None
    if a.use_data_lengths:
        data_lengths = inputs[i]
        i += 1
    if a.use_label_lengths:
        label_lengths = inputs[i]
    if a.use_data_lengths and a.use_label_lengths:
        return jax.vmap(lambda lg, lb, dl, ll: _ctc_loss_one(
            lg, lb, a, dl, ll), in_axes=(1, 0, 0, 0))(
                data, label, data_lengths, label_lengths)
    if a.use_data_lengths:
        return jax.vmap(lambda lg, lb, dl: _ctc_loss_one(lg, lb, a, dl),
                        in_axes=(1, 0, 0))(data, label, data_lengths)
    if a.use_label_lengths:
        return jax.vmap(lambda lg, lb, ll: _ctc_loss_one(
            lg, lb, a, None, ll), in_axes=(1, 0, 0))(data, label,
                                                     label_lengths)
    return jax.vmap(lambda lg, lb: _ctc_loss_one(lg, lb, a),
                    in_axes=(1, 0))(data, label)


def _ctc_args(a):
    names = ["data", "label"]
    if a.get("use_data_lengths"):
        names.append("data_lengths")
    if a.get("use_label_lengths"):
        names.append("label_lengths")
    return names


register("_contrib_CTCLoss", _ctc_loss, arg_names=_ctc_args,
         attrs={"use_data_lengths": False, "use_label_lengths": False,
                "blank_label": "first"},
         aliases=("CTCLoss", "ctc_loss", "_contrib_ctc_loss"),
         loss_like=True)


# -------------------------------------------------------------- count_sketch


def _count_sketch(a, data, h, s):
    """Count-sketch projection to out_dim (contrib/count_sketch.cc):
    out[n, h[i]] += s[i] * data[n, i]."""
    out_dim = int(a.out_dim)
    idx = h.reshape(-1).astype(jnp.int32)
    sign = s.reshape(-1)
    contrib = data * sign[None, :]
    out = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    return out.at[..., idx].add(contrib)


register("_contrib_count_sketch", _count_sketch,
         arg_names=["data", "h", "s"],
         attrs={"out_dim": Required(int), "processing_batch_size": 32})
