"""Operator library: importing this package registers every op.

See registry.py for the design; families mirror SURVEY.md §2.3 / Appendix A.
"""
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import random_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import linalg  # noqa: F401
from . import rnn  # noqa: F401
from . import contrib  # noqa: F401
from . import spatial  # noqa: F401
from . import custom  # noqa: F401
from . import attention  # noqa: F401
from .registry import OpDef, get_op, list_ops, op_exists, register  # noqa: F401
