"""Flash attention as a Pallas TPU kernel.

Beyond reference parity (the reference has no attention operator —
SURVEY.md §5 'Long-context'), but the hot op of any long-context model, so
it gets the full TPU treatment per /opt/skills/guides/pallas_guide.md:

- grid (batch*heads, q_blocks, kv_blocks), iterated sequentially on-core
  so VMEM scratch (running max / normalizer / accumulator) carries the
  online-softmax state across the kv dimension;
- q@k^T and p@v on the MXU with f32 accumulation (preferred_element_type);
- causal masking per block via broadcasted iotas;
- output written once, on the last kv block, normalized by the running sum.

Backward runs through a jax.custom_vjp whose residual-free bwd recomputes
with the pure-jnp reference (identical math) — the standard
recompute-in-bwd tradeoff flash attention makes anyway.

On non-TPU backends the kernel runs in interpret mode for small shapes
(tests) and falls back to the jnp reference otherwise.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register

try:  # pallas import kept soft so CPU-only installs still import this module
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    _HAVE_PALLAS = False

NEG_INF = -1e30


def _reference(q, k, v, scale, causal):
    """Pure-jnp oracle. (BH, T, D) layout. Materializes the T^2 score
    matrix — tests and small shapes only."""
    s = jnp.einsum("btd,bsd->bts", q, k).astype(jnp.float32) * scale
    if causal:
        t = s.shape[1]
        srng = s.shape[2]
        mask = jnp.arange(srng)[None, :] <= jnp.arange(t)[:, None]
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bts,bsd->btd", p.astype(v.dtype), v)


def _streaming(q, k, v, scale, causal, block=512):
    """lax.scan flash-style attention, (BH, T, D) layout: O(T) residuals,
    so its VJP is the memory-efficient backward recompute path."""
    bh, t, d = q.shape
    s_len = k.shape[1]
    nblk = -(-s_len // block)
    pad = nblk * block - s_len
    kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0))) if pad else k
    vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0))) if pad else v
    kb = kp.reshape(bh, nblk, block, d).transpose(1, 0, 2, 3)
    vb = vp.reshape(bh, nblk, block, d).transpose(1, 0, 2, 3)
    q_idx = jnp.arange(t)

    def body(carry, blk):
        m_prev, l_prev, o_prev = carry
        kc, vc, bi = blk
        s = jnp.einsum("btd,bsd->bts", q, kc).astype(jnp.float32) * scale
        k_idx = bi * block + jnp.arange(block)
        valid = k_idx[None, :] < s_len
        if causal:
            valid = valid & (k_idx[None, :] <= q_idx[:, None])
        s = jnp.where(valid[None], s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1)
        o_new = o_prev * alpha[..., None] + jnp.einsum(
            "bts,bsd->btd", p, vc.astype(jnp.float32))
        return (m_new, l_new, o_new), None

    m0 = jnp.full((bh, t), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, t), jnp.float32)
    o0 = jnp.zeros((bh, t, d), jnp.float32)
    (m, l, o), _ = jax.lax.scan(body, (m0, l0, o0),
                                (kb, vb, jnp.arange(nblk)))
    return (o / jnp.maximum(l, 1e-20)[..., None]).astype(q.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                scale, causal, block_q, block_k, kv_len):
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    i = pl.program_id(1)
    # causal: a kv block strictly above the diagonal contributes nothing —
    # skip its matmuls entirely (halves the causal FLOPs)
    live = (j * block_k <= (i + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _accumulate():
        q = q_ref[0]  # (block_q, D)
        k = k_ref[0]  # (block_k, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale

        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(cols <= rows, s, NEG_INF)
        v_blk = v_ref[0]
        if kv_len % block_k != 0:
            # tail block: padded KV columns must not enter the softmax,
            # and padded V rows may be garbage/NaN — 0 * NaN = NaN, so
            # zero them instead of relying on p == 0
            s = jnp.where(cols < kv_len, s, NEG_INF)
            vrows = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, 1), 0)
            v_blk = jnp.where(vrows < kv_len, v_blk, 0)

        m_prev = m_ref[:, :1]                      # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                     # (block_q, block_k) f32
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nj - 1)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[...] /
                    jnp.where(l == 0, 1.0, l)).astype(o_ref.dtype)


def _flash_call(q, k, v, scale, causal, block_q, block_k, interpret):
    bh, t, d = q.shape
    s_len = k.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, s_len)
    grid = (bh, pl.cdiv(t, block_q), pl.cdiv(s_len, block_k))
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               kv_len=s_len)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash3(q, k, v, scale, causal, block_q, block_k):
    if not _HAVE_PALLAS:
        return _reference(q, k, v, scale, causal)
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        # interpret mode exercises the kernel logic on CPU for small
        # problems; big CPU shapes take the reference path
        if q.shape[0] * q.shape[1] * k.shape[1] <= 1 << 22:
            return _flash_call(q, k, v, scale, causal, block_q, block_k,
                               interpret=True)
        return _reference(q, k, v, scale, causal)
    return _flash_call(q, k, v, scale, causal, block_q, block_k,
                       interpret=False)


def _flash3_fwd(q, k, v, scale, causal, block_q, block_k):
    return _flash3(q, k, v, scale, causal, block_q, block_k), (q, k, v)


def _flash3_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v = res
    # recompute through the streaming implementation: its scan keeps O(T)
    # residuals, so long-context training never materializes T^2 scores
    _, vjp = jax.vjp(lambda a, b, c: _streaming(a, b, c, scale, causal,
                                                block=block_k),
                     q, k, v)
    return vjp(g)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=512,
                    block_k=1024):
    """Multi-head attention, (B, H, T, D) layout (B/H merged internally)."""
    b, h, t, d = q.shape
    s_len = k.shape[2]
    scale = float(sm_scale) if sm_scale is not None else 1.0 / (d ** 0.5)
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, s_len, d)
    vf = v.reshape(b * h, s_len, d)
    out = _flash3(qf, kf, vf, scale, bool(causal), int(block_q),
                  int(block_k))
    return out.reshape(b, h, t, d)


def _flash_op(a, q, k, v):
    return flash_attention(q, k, v, causal=a.causal,
                           sm_scale=(a.sm_scale if a.sm_scale != 0.0
                                     else None),
                           block_q=a.block_q, block_k=a.block_k)


register("_contrib_FlashAttention", _flash_op,
         arg_names=["query", "key", "value"],
         attrs={"causal": False, "sm_scale": 0.0, "block_q": 512,
                "block_k": 1024},
         aliases=("flash_attention",))
