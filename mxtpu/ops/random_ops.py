"""Random sampling ops on the TPU-native threefry PRNG.

Parity: src/operator/random/{sample_op.cc:48-147, multisample_op.cc:380-389,
sample_multinomial_op.cc}. The reference uses per-device PRNG resources
(ResourceManager kRandom); here every sampler is a pure function of an explicit
threefry key (SURVEY.md §2.3 'needs TPU PRNG design (threefry)'), threaded by the
imperative invoker from mxtpu.random global state or by the executor per step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .registry import Required, register


def _shape_dtype(a):
    shape = tuple(a.shape) if a.shape else ()
    dtype = _np.dtype(a.dtype if a.dtype and a.dtype != "None" else "float32")
    return shape, dtype


_SAMPLER_DEFAULTS = {"low": 0.0, "high": 1.0, "loc": 0.0, "scale": 1.0,
                     "lam": 1.0, "alpha": 1.0, "beta": 1.0, "k": 1,
                     "p": 1.0, "mu": 1.0, "sigma": 1.0}


def _sampler(name, draw, lead=()):
    """``lead``: the distribution's own parameters, in the reference's
    declared order (src/operator/random/sample_op.cc) — they come FIRST in
    attrs_spec so positional calls like ``nd.random_normal(0, 1.0,
    shape=...)`` map loc/scale the way the reference signature does."""
    attrs = {k: _SAMPLER_DEFAULTS[k] for k in lead}
    # reference positional order after the distribution params:
    # shape, ctx, dtype (sample_op.cc SampleUniformParam et al.)
    attrs.update({"shape": (), "ctx": "", "dtype": "float32"})
    for k, v in _SAMPLER_DEFAULTS.items():
        attrs.setdefault(k, v)

    def impl(a, rng):
        shape, dtype = _shape_dtype(a)
        return draw(a, rng, shape, dtype)

    register(name, impl, arg_names=[], needs_rng=True, attrs=attrs)


_sampler("_random_uniform",
         lambda a, r, s, d: jax.random.uniform(r, s, d, a.low, a.high),
         lead=("low", "high"))
_sampler("_random_normal",
         lambda a, r, s, d: a.loc + a.scale * jax.random.normal(r, s, d),
         lead=("loc", "scale"))
_sampler("_random_gamma",
         lambda a, r, s, d: (a.beta * jax.random.gamma(r, a.alpha, s)).astype(d),
         lead=("alpha", "beta"))
_sampler("_random_exponential",
         lambda a, r, s, d: (jax.random.exponential(r, s) / a.lam).astype(d),
         lead=("lam",))
_sampler("_random_poisson",
         lambda a, r, s, d: jax.random.poisson(r, a.lam, s).astype(d),
         lead=("lam",))
_sampler("_random_negative_binomial",
         lambda a, r, s, d: _neg_binomial(r, float(a.k), float(a.p), s).astype(d),
         lead=("k", "p"))
_sampler("_random_generalized_negative_binomial",
         lambda a, r, s, d: _gen_neg_binomial(r, float(a.mu), float(a.alpha), s).astype(d),
         lead=("mu", "alpha"))


def _neg_binomial(rng, k, p, shape):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, shape) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, shape)


def _gen_neg_binomial(rng, mu, alpha, shape):
    if alpha == 0:
        return jax.random.poisson(rng, mu, shape)
    k1, k2 = jax.random.split(rng)
    r = 1.0 / alpha
    lam = jax.random.gamma(k1, r, shape) * (mu * alpha)
    return jax.random.poisson(k2, lam, shape)


# ---- per-row multisample ops: distribution params come from input tensors ----


def _multisampler(name, draw, two_param=True):
    def impl(a, rng, *params):
        shape = tuple(a.shape) if a.shape else ()
        out_shape = params[0].shape + shape
        return draw(rng, params, out_shape).astype(
            _np.dtype(a.dtype) if a.dtype and a.dtype != "None" else params[0].dtype)

    register(name, impl,
             arg_names=["lhs", "rhs"] if two_param else ["data"],
             needs_rng=True, attrs={"shape": (), "dtype": "None"})


def _rs(p, out_shape):
    """Broadcast a per-row param tensor against trailing sample dims."""
    return p.reshape(p.shape + (1,) * (len(out_shape) - p.ndim))


_multisampler("sample_uniform",
              lambda r, ps, s: jax.random.uniform(r, s) * (_rs(ps[1], s) - _rs(ps[0], s)) + _rs(ps[0], s))
_multisampler("sample_normal",
              lambda r, ps, s: _rs(ps[0], s) + _rs(ps[1], s) * jax.random.normal(r, s))
_multisampler("sample_gamma",
              lambda r, ps, s: jax.random.gamma(r, jnp.broadcast_to(_rs(ps[0], s), s)) * _rs(ps[1], s))
_multisampler("sample_exponential",
              lambda r, ps, s: jax.random.exponential(r, s) / _rs(ps[0], s), two_param=False)
_multisampler("sample_poisson",
              lambda r, ps, s: jax.random.poisson(r, jnp.broadcast_to(_rs(ps[0], s), s)).astype(jnp.float32),
              two_param=False)


def _row_neg_binomial(r, ps, s):
    k1, k2 = jax.random.split(r)
    k = jnp.broadcast_to(_rs(ps[0], s), s)
    p = jnp.broadcast_to(_rs(ps[1], s), s)
    lam = jax.random.gamma(k1, k) * ((1 - p) / p)
    return jax.random.poisson(k2, lam).astype(jnp.float32)


def _row_gen_neg_binomial(r, ps, s):
    k1, k2 = jax.random.split(r)
    mu = jnp.broadcast_to(_rs(ps[0], s), s)
    alpha = jnp.broadcast_to(_rs(ps[1], s), s)
    # alpha -> 0 degenerates to poisson(mu); clamp for the gamma draw
    safe_alpha = jnp.maximum(alpha, 1e-8)
    lam = jax.random.gamma(k1, 1.0 / safe_alpha) * (mu * safe_alpha)
    lam = jnp.where(alpha <= 1e-8, mu, lam)
    return jax.random.poisson(k2, lam).astype(jnp.float32)


_multisampler("sample_negative_binomial", _row_neg_binomial)
_multisampler("sample_generalized_negative_binomial", _row_gen_neg_binomial)


def _sample_multinomial(a, rng, data):
    n = int(a.shape[0]) if a.shape else 1
    logits = jnp.log(jnp.clip(data, 1e-30, None))
    if data.ndim == 1:
        out = jax.random.categorical(rng, logits, shape=(n,))
    else:
        out = jax.random.categorical(rng, logits[:, None, :], axis=-1,
                                     shape=(data.shape[0], n))
    if not a.shape:
        out = out.reshape(out.shape[:-1] + ()) if False else jnp.squeeze(out, -1)
    out = out.astype(_np.dtype(a.dtype))
    if a.get_prob:
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1) if data.ndim > 1 else
            jax.nn.log_softmax(logits)[None],
            out.reshape(data.shape[0] if data.ndim > 1 else 1, -1).astype(jnp.int32),
            axis=-1)
        return out, logp.reshape(out.shape).astype(jnp.float32)
    return out


register("sample_multinomial", _sample_multinomial, arg_names=["data"],
         needs_rng=True,
         attrs={"shape": (), "get_prob": False, "dtype": "int32"},
         num_outputs=lambda a: 2 if a.get_prob else 1)
