"""Spatial / warping / region operators.

TPU-native equivalents of the reference's hand-written CPU/CUDA kernels:
SpatialTransformer (src/operator/spatial_transformer-inl.h), GridGenerator
(grid_generator-inl.h), BilinearSampler (bilinear_sampler-inl.h), ROIPooling
(roi_pooling-inl.h), Correlation (correlation-inl.h), and the contrib region
ops Proposal/MultiProposal (contrib/proposal-inl.h), PSROIPooling
(contrib/psroi_pooling-inl.h), DeformableConvolution
(contrib/deformable_convolution-inl.h), DeformablePSROIPooling.

Design: all warping is expressed as gather + bilinear weights over static
shapes, which XLA lowers to fused dynamic-gather kernels; there is no
scatter-heavy backward to hand-write because gradients come from jax.grad of
the same gather expression. NMS inside Proposal reuses the scan-based NMS of
the multibox family.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .registry import Required, register
from .contrib import _box_iou_corner, _nms_scan

# ------------------------------------------------------------ bilinear sample


def _bilinear_gather(data, gx, gy):
    """Sample data (C,H,W) at float pixel coords gx,gy (...,) -> (C, ...).

    Out-of-range samples are zero (reference bilinear_sampler semantics:
    zero padding outside [-1,1] grid)."""
    C, H, W = data.shape
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1 = x0 + 1
    y1 = y0 + 1
    wx1 = gx - x0
    wy1 = gy - y0
    wx0 = 1.0 - wx1
    wy0 = 1.0 - wy1

    def tap(xi, yi, w):
        inside = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        v = data[:, yc, xc]  # (C, ...)
        return v * (w * inside.astype(data.dtype))

    return (tap(x0, y0, wx0 * wy0) + tap(x1, y0, wx1 * wy0) +
            tap(x0, y1, wx0 * wy1) + tap(x1, y1, wx1 * wy1))


def _grid_to_pixels(grid, H, W):
    """MXNet grid convention: grid (2, Ho, Wo) with rows (x, y) in [-1, 1];
    maps to pixel coords [(x+1)/2*(W-1), (y+1)/2*(H-1)]."""
    gx = (grid[0] + 1.0) * (W - 1) / 2.0
    gy = (grid[1] + 1.0) * (H - 1) / 2.0
    return gx, gy


def _bilinear_sampler(a, data, grid):
    """data (N,C,H,W), grid (N,2,Ho,Wo) -> (N,C,Ho,Wo)."""
    H, W = data.shape[2], data.shape[3]

    def one(d, g):
        gx, gy = _grid_to_pixels(g, H, W)
        return _bilinear_gather(d, gx, gy)

    return jax.vmap(one)(data, grid)


register("BilinearSampler", _bilinear_sampler, arg_names=["data", "grid"],
         attrs={})

# ------------------------------------------------------------- GridGenerator


def _affine_grid(affine, H, W):
    """affine (6,) row-major 2x3 -> grid (2,H,W) in [-1,1] (x,y)."""
    ys = jnp.linspace(-1.0, 1.0, H)
    xs = jnp.linspace(-1.0, 1.0, W)
    xg, yg = jnp.meshgrid(xs, ys)  # (H,W)
    ones = jnp.ones_like(xg)
    src = jnp.stack([xg, yg, ones], axis=0).reshape(3, -1)  # (3, H*W)
    th = affine.reshape(2, 3)
    out = th @ src  # (2, H*W)
    return out.reshape(2, H, W)


def _grid_generator(a, data):
    H, W = int(a.target_shape[0]), int(a.target_shape[1])
    if a.transform_type == "affine":
        return jax.vmap(lambda t: _affine_grid(t, H, W))(data)
    # warp: data (N,2,H,W) flow field in pixels; output = identity + flow,
    # normalized to [-1,1] (grid_generator-inl.h warp branch)
    xs = jnp.arange(W, dtype=data.dtype)
    ys = jnp.arange(H, dtype=data.dtype)
    xg, yg = jnp.meshgrid(xs, ys)
    gx = (data[:, 0] + xg) * 2.0 / jnp.maximum(W - 1, 1) - 1.0
    gy = (data[:, 1] + yg) * 2.0 / jnp.maximum(H - 1, 1) - 1.0
    return jnp.stack([gx, gy], axis=1)


register("GridGenerator", _grid_generator,
         attrs={"transform_type": Required(str), "target_shape": (0, 0)})

# -------------------------------------------------------- SpatialTransformer


def _spatial_transformer(a, data, loc):
    """Affine spatial transformer network (spatial_transformer-inl.h):
    loc (N,6) affine params -> sample data onto target_shape grid."""
    H, W = int(a.target_shape[0]), int(a.target_shape[1])
    Hs, Ws = data.shape[2], data.shape[3]

    def one(d, t):
        grid = _affine_grid(t, H, W)  # (2,H,W) in [-1,1]
        gx, gy = _grid_to_pixels(grid, Hs, Ws)
        return _bilinear_gather(d, gx, gy)

    return jax.vmap(one)(data, loc)


register("SpatialTransformer", _spatial_transformer,
         arg_names=["data", "loc"],
         attrs={"target_shape": Required(tuple),
                "transform_type": "affine", "sampler_type": "bilinear"})

# ---------------------------------------------------------------- ROIPooling


def _roi_pool_one(feat, roi, pooled_h, pooled_w, spatial_scale):
    """feat (C,H,W), roi (5,) [batch_idx,x1,y1,x2,y2] image coords ->
    (C,ph,pw). Max pool over adaptive bins (roi_pooling-inl.h)."""
    C, H, W = feat.shape
    x1 = jnp.round(roi[1] * spatial_scale)
    y1 = jnp.round(roi[2] * spatial_scale)
    x2 = jnp.round(roi[3] * spatial_scale)
    y2 = jnp.round(roi[4] * spatial_scale)
    rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
    rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
    bin_w = rw / pooled_w
    bin_h = rh / pooled_h

    ph = jnp.arange(pooled_h, dtype=feat.dtype)
    pw = jnp.arange(pooled_w, dtype=feat.dtype)
    hstart = jnp.clip(jnp.floor(ph * bin_h) + y1, 0, H - 1)[:, None]
    hend = jnp.clip(jnp.ceil((ph + 1) * bin_h) + y1, 0, H)[:, None]
    wstart = jnp.clip(jnp.floor(pw * bin_w) + x1, 0, W - 1)[None, :]
    wend = jnp.clip(jnp.ceil((pw + 1) * bin_w) + x1, 0, W)[None, :]

    yy = jnp.arange(H, dtype=feat.dtype)
    xx = jnp.arange(W, dtype=feat.dtype)
    # membership masks (ph,H) / (pw,W); bins are small so mask+max is fine
    in_y = (yy[None, :] >= hstart) & (yy[None, :] < hend)  # (ph, H)
    in_x = (xx[None, :] >= wstart.T) & (xx[None, :] < wend.T)  # (pw, W)
    m = in_y[:, None, :, None] & in_x[None, :, None, :]  # (ph,pw,H,W)
    neg = jnp.asarray(-_np.inf, feat.dtype)
    masked = jnp.where(m[None], feat[:, None, None, :, :], neg)
    out = jnp.max(masked, axis=(3, 4))  # (C,ph,pw)
    return jnp.where(jnp.isfinite(out), out, 0.0)


def _roi_pooling(a, data, rois):
    ph, pw = int(a.pooled_size[0]), int(a.pooled_size[1])
    scale = float(a.spatial_scale)

    def one(roi):
        feat = data[roi[0].astype(jnp.int32)]
        return _roi_pool_one(feat, roi, ph, pw, scale)

    return jax.vmap(one)(rois)


register("ROIPooling", _roi_pooling, arg_names=["data", "rois"],
         attrs={"pooled_size": Required(tuple),
                "spatial_scale": Required(float)})

# -------------------------------------------------------------- PSROIPooling


def _psroi_pool_one(feat, roi, a):
    """Position-sensitive ROI pooling (contrib/psroi_pooling-inl.h):
    feat (C,H,W) with C = output_dim * group^2; average pool the
    position-sensitive channel of each pooled bin over that bin's extent.
    group_size=0 means group_size=pooled_size (reference default)."""
    C, H, W = feat.shape
    pooled = int(a.pooled_size)
    group = int(a.group_size) or pooled
    odim = int(a.output_dim)
    scale = float(a.spatial_scale)
    x1 = roi[1] * scale
    y1 = roi[2] * scale
    x2 = roi[3] * scale
    y2 = roi[4] * scale
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_w = rw / pooled
    bin_h = rh / pooled

    yy = jnp.arange(H, dtype=feat.dtype)
    xx = jnp.arange(W, dtype=feat.dtype)
    bi = jnp.arange(pooled, dtype=feat.dtype)
    hstart = jnp.floor(y1 + bi * bin_h)
    hend = jnp.ceil(y1 + (bi + 1) * bin_h)
    wstart = jnp.floor(x1 + bi * bin_w)
    wend = jnp.ceil(x1 + (bi + 1) * bin_w)
    in_y = (yy[None, :] >= hstart[:, None]) & (yy[None, :] < hend[:, None])
    in_x = (xx[None, :] >= wstart[:, None]) & (xx[None, :] < wend[:, None])
    # bin (by,bx) membership mask over pixels: (pooled, pooled, H, W)
    m = (in_y[:, None, :, None] & in_x[None, :, None, :]).astype(feat.dtype)
    # channel group of each pooled bin: gy = by*group//pooled
    gsel = (jnp.arange(pooled) * group // pooled).astype(jnp.int32)
    f = feat.reshape(odim, group, group, H, W)
    fbin = f[:, gsel][:, :, gsel]  # (odim, pooled, pooled, H, W)
    num = jnp.einsum("obcyx,bcyx->obc", fbin, m)
    den = jnp.maximum(jnp.einsum("bcyx->bc", m), 1.0)
    return num / den[None]


def _psroi_pooling(a, data, rois):
    def one(roi):
        feat = data[roi[0].astype(jnp.int32)]
        return _psroi_pool_one(feat, roi, a)

    return jax.vmap(one)(rois)


register("_contrib_PSROIPooling", _psroi_pooling, arg_names=["data", "rois"],
         attrs={"spatial_scale": Required(float), "output_dim": Required(int),
                "pooled_size": Required(int), "group_size": 0},
         aliases=("PSROIPooling",))

# --------------------------------------------------------------- Correlation


def _correlation(a, data1, data2):
    """FlowNet correlation layer (correlation-inl.h): for each displacement
    d in a (2*max_disp/stride2+1)^2 neighbourhood, output the patchwise
    inner product of data1(x) and data2(x+d), averaged over channels*patch."""
    pad = int(a.pad_size)
    kernel = int(a.kernel_size)
    maxd = int(a.max_displacement)
    s1 = int(a.stride1)
    s2 = int(a.stride2)
    mult = bool(a.is_multiply)
    N, C, H, W = data1.shape
    d1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    Hp, Wp = H + 2 * pad, W + 2 * pad
    nd = 2 * (maxd // s2) + 1
    bord = maxd + (kernel - 1) // 2
    out_h = -(-(Hp - 2 * bord) // s1)
    out_w = -(-(Wp - 2 * bord) // s1)
    k2 = kernel // 2
    ys = bord + jnp.arange(out_h) * s1
    xs = bord + jnp.arange(out_w) * s1

    def patch(img, cy, cx):
        # (C, kernel, kernel) patch centred at cy,cx
        return lax.dynamic_slice(
            img, (0, cy - k2, cx - k2), (C, kernel, kernel))

    def one_pair(a1, a2):
        def at_disp(dy, dx):
            def at_pos(cy, cx):
                p1 = patch(a1, cy, cx)
                p2 = patch(a2, cy + dy, cx + dx)
                if mult:
                    return jnp.sum(p1 * p2)
                return jnp.sum(jnp.abs(p1 - p2))
            return jax.vmap(lambda cy: jax.vmap(
                lambda cx: at_pos(cy, cx))(xs))(ys)

        disps = jnp.arange(-(maxd // s2), maxd // s2 + 1) * s2
        rows = jax.vmap(lambda dy: jax.vmap(
            lambda dx: at_disp(dy, dx))(disps))(disps)
        # (nd, nd, out_h, out_w) -> (nd*nd, out_h, out_w)
        return rows.reshape(nd * nd, out_h, out_w) / (C * kernel * kernel)

    return jax.vmap(one_pair)(d1, d2)


register("Correlation", _correlation, arg_names=["data1", "data2"],
         attrs={"kernel_size": 1, "max_displacement": 1, "stride1": 1,
                "stride2": 1, "pad_size": 0, "is_multiply": True})

# --------------------------------------------------- DeformableConvolution


def _deformable_conv(a, data, offset, weight, bias=None):
    """Deformable convolution v1 (contrib/deformable_convolution-inl.h):
    sampling locations of a standard conv are perturbed by a learned
    per-position offset field. Implemented as bilinear gather of the
    deformed im2col columns followed by a dot — the gathers vectorize over
    (output position, kernel tap) and XLA fuses them."""
    kh, kw = int(a.kernel[0]), int(a.kernel[1])
    sh, sw = (int(x) for x in (tuple(a.stride) or (1, 1)))
    ph, pw = (int(x) for x in (tuple(a.pad) or (0, 0)))
    dh, dw = (int(x) for x in (tuple(a.dilate) or (1, 1)))
    N, C, H, W = data.shape
    F = int(a.num_filter)
    G = int(a.num_group)
    DG = int(a.num_deformable_group)
    out_h = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    out_w = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    base_y = (jnp.arange(out_h) * sh - ph)[:, None, None]  # (oh,1,1)
    base_x = (jnp.arange(out_w) * sw - pw)[None, :, None]  # (1,ow,1)
    ky = (jnp.arange(kh) * dh)[None, None, :, None]  # (1,1,kh,1)
    kx = (jnp.arange(kw) * dw)[None, None, None, :]  # (1,1,1,kw)

    def one(d, off):
        # off (2*DG*kh*kw, oh, ow): one offset field per deformable group,
        # each applied to its C/DG slice of input channels.
        off = off.reshape(DG, kh * kw, 2, out_h, out_w)

        def per_dg(o_dg, d_dg):
            dy = jnp.transpose(o_dg[:, 0], (1, 2, 0)).reshape(
                out_h, out_w, kh, kw)
            dx = jnp.transpose(o_dg[:, 1], (1, 2, 0)).reshape(
                out_h, out_w, kh, kw)
            gy = base_y[..., None] + ky[0] + dy  # (oh,ow,kh,kw)
            gx = base_x[..., None] + kx[0] + dx
            return _bilinear_gather(d_dg, gx, gy)  # (C/DG,oh,ow,kh,kw)

        cols = jax.vmap(per_dg)(off, d.reshape(DG, C // DG, H, W))
        return cols.reshape(C, out_h, out_w, kh, kw)

    cols = jax.vmap(one)(data, offset)  # (N,C,oh,ow,kh,kw)
    # grouped conv: weight is (F, C/G, kh, kw); each group of F/G filters
    # sees its own C/G slice of input channels.
    cols_g = cols.reshape(N, G, C // G, out_h, out_w, kh, kw)
    w_g = weight.reshape(G, F // G, C // G, kh, kw)
    out = jnp.einsum("ngchwyx,gfcyx->ngfhw", cols_g, w_g).reshape(
        N, F, out_h, out_w)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


register("_contrib_DeformableConvolution", _deformable_conv,
         arg_names=lambda a: (["data", "offset", "weight"]
                              if a.get("no_bias", True)
                              else ["data", "offset", "weight", "bias"]),
         attrs={"kernel": Required(tuple), "stride": (), "dilate": (),
                "pad": (), "num_filter": Required(int), "num_group": 1,
                "num_deformable_group": 1, "no_bias": True,
                "workspace": 1024, "layout": None},
         aliases=("DeformableConvolution",))


def _deformable_psroi_pooling(a, data, rois, trans=None):
    """Deformable PSROIPooling (contrib/deformable_psroi_pooling-inl.h):
    PSROI bins shifted by normalized learned offsets `trans`."""
    group = int(a.group_size)
    odim = int(a.output_dim)
    part = int(a.part_size) or group
    scale = float(a.spatial_scale)
    trans_std = float(a.trans_std)
    pooled = int(a.pooled_size)
    no_trans = bool(a.no_trans)
    C, H, W = data.shape[1], data.shape[2], data.shape[3]

    def one(roi, tr):
        feat = data[roi[0].astype(jnp.int32)]
        x1 = roi[1] * scale - 0.5
        y1 = roi[2] * scale - 0.5
        x2 = (roi[3] + 1.0) * scale - 0.5
        y2 = (roi[4] + 1.0) * scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w = rw / pooled
        bin_h = rh / pooled
        sub = int(a.sample_per_part)  # sampling taps per bin edge
        gi = jnp.arange(pooled)
        f = feat.reshape(odim, group, group, H, W)

        def bin_val(o, by, bx):
            gy = jnp.minimum(by * group // pooled, group - 1)
            gx = jnp.minimum(bx * group // pooled, group - 1)
            if no_trans:
                ty = tx = 0.0
            else:
                py = jnp.minimum(by * part // pooled, part - 1)
                px = jnp.minimum(bx * part // pooled, part - 1)
                cls = o * 0  # class-agnostic offsets (dim 0)
                ty = tr[2 * cls, py, px] * trans_std
                tx = tr[2 * cls + 1, py, px] * trans_std
            ys = y1 + (by + (jnp.arange(sub) + 0.5) / sub) * bin_h \
                + ty * rh
            xs = x1 + (bx + (jnp.arange(sub) + 0.5) / sub) * bin_w \
                + tx * rw
            yg, xg = jnp.meshgrid(ys, xs, indexing="ij")
            ch = f[o, gy, gx]  # (H,W)
            v = _bilinear_gather(ch[None], xg, yg)[0]  # (sub,sub)
            return jnp.mean(v)

        ov = jax.vmap(lambda o: jax.vmap(lambda by: jax.vmap(
            lambda bx: bin_val(o, by, bx))(gi))(gi))(
                jnp.arange(odim))
        return ov  # (odim, pooled, pooled)

    if no_trans:
        trans_in = jnp.zeros((rois.shape[0], 2, part, part), data.dtype)
    else:
        trans_in = trans
    return jax.vmap(one)(rois, trans_in)


register("_contrib_DeformablePSROIPooling", _deformable_psroi_pooling,
         arg_names=lambda a: (["data", "rois"] if a.get("no_trans")
                              else ["data", "rois", "trans"]),
         attrs={"spatial_scale": Required(float), "output_dim": Required(int),
                "group_size": Required(int), "pooled_size": Required(int),
                "part_size": 0, "sample_per_part": 4, "trans_std": 0.0,
                "no_trans": False},
         aliases=("DeformablePSROIPooling",))

# ------------------------------------------------------- Proposal (RPN)


def _gen_anchors(base_size, scales, ratios):
    """Standard RPN anchor generation (contrib/proposal-inl.h GenerateAnchors)."""
    base = _np.array([0, 0, base_size - 1, base_size - 1], dtype=_np.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    anchors = []
    for r in ratios:
        size = w * h
        ws = _np.round(_np.sqrt(size / r))
        hs = _np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                            cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return _np.array(anchors, dtype=_np.float32)  # (A,4)


def _proposal_one(score, bbox_deltas, im_info, a):
    """One image. score (2A, H, W) [bg scores then fg], bbox (4A, H, W)."""
    scales = [float(s) for s in a.scales]
    ratios = [float(r) for r in a.ratios]
    stride = int(a.feature_stride)
    A = len(scales) * len(ratios)
    H, W = score.shape[1], score.shape[2]
    anchors = jnp.asarray(_gen_anchors(stride, scales, ratios))  # (A,4)
    sx = jnp.arange(W, dtype=jnp.float32) * stride
    sy = jnp.arange(H, dtype=jnp.float32) * stride
    shift_x, shift_y = jnp.meshgrid(sx, sy)
    shifts = jnp.stack([shift_x, shift_y, shift_x, shift_y],
                       axis=-1).reshape(-1, 4)  # (H*W,4)
    all_anchors = (anchors[None, :, :] + shifts[:, None, :]).reshape(-1, 4)

    fg = jnp.transpose(score[A:], (1, 2, 0)).reshape(-1)  # (H*W*A,)
    deltas = jnp.transpose(bbox_deltas.reshape(A, 4, H, W),
                           (2, 3, 0, 1)).reshape(-1, 4)

    # decode
    widths = all_anchors[:, 2] - all_anchors[:, 0] + 1.0
    heights = all_anchors[:, 3] - all_anchors[:, 1] + 1.0
    ctr_x = all_anchors[:, 0] + 0.5 * (widths - 1.0)
    ctr_y = all_anchors[:, 1] + 0.5 * (heights - 1.0)
    dx, dy, dw, dh = deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3]
    pred_ctr_x = dx * widths + ctr_x
    pred_ctr_y = dy * heights + ctr_y
    pred_w = jnp.exp(dw) * widths
    pred_h = jnp.exp(dh) * heights
    boxes = jnp.stack([pred_ctr_x - 0.5 * (pred_w - 1),
                       pred_ctr_y - 0.5 * (pred_h - 1),
                       pred_ctr_x + 0.5 * (pred_w - 1),
                       pred_ctr_y + 0.5 * (pred_h - 1)], axis=-1)
    im_h, im_w = im_info[0], im_info[1]
    boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, im_w - 1),
                       jnp.clip(boxes[:, 1], 0, im_h - 1),
                       jnp.clip(boxes[:, 2], 0, im_w - 1),
                       jnp.clip(boxes[:, 3], 0, im_h - 1)], axis=-1)
    ws = boxes[:, 2] - boxes[:, 0] + 1
    hs = boxes[:, 3] - boxes[:, 1] + 1
    min_size = float(a.rpn_min_size) * im_info[2]
    keep = (ws >= min_size) & (hs >= min_size)
    fg = jnp.where(keep, fg, -jnp.inf)

    pre = int(a.rpn_pre_nms_top_n)
    post = int(a.rpn_post_nms_top_n)
    K = boxes.shape[0]
    pre = min(pre, K) if pre > 0 else K
    order = jnp.argsort(-fg)[:pre]
    b = boxes[order]
    s = fg[order]
    keep = _nms_scan(b, s, jnp.zeros_like(s), float(a.threshold), True)
    s = jnp.where(keep, s, -jnp.inf)
    order2 = jnp.argsort(-s)[:post]
    # When NMS keeps fewer than post proposals, cycle through the kept ones
    # instead of emitting suppressed boxes (reference proposal.cc pads from
    # the kept set).
    num_kept = jnp.maximum(jnp.sum(jnp.isfinite(s[order2])), 1)
    slot = jnp.arange(post)
    order2 = order2[jnp.where(slot < num_kept, slot, slot % num_kept)]
    out_boxes = b[order2]
    out_scores = jnp.where(jnp.isfinite(s[order2]), s[order2], 0.0)
    rois = jnp.concatenate([jnp.zeros((post, 1), b.dtype), out_boxes],
                           axis=-1)
    return rois, out_scores.reshape(post, 1)


def _proposal(a, cls_prob, bbox_pred, im_info):
    rois, scores = jax.vmap(
        lambda s, b, i: _proposal_one(s, b, i, a))(cls_prob, bbox_pred,
                                                   im_info)
    n, p = rois.shape[0], rois.shape[1]
    batch_idx = jnp.broadcast_to(
        jnp.arange(n, dtype=rois.dtype)[:, None, None], (n, p, 1))
    rois = jnp.concatenate([batch_idx, rois[..., 1:]], axis=-1)
    rois = rois.reshape(n * p, 5)
    if a.output_score:
        return rois, scores.reshape(n * p, 1)
    return rois


register("_contrib_Proposal", _proposal,
         arg_names=["cls_prob", "bbox_pred", "im_info"],
         attrs={"rpn_pre_nms_top_n": 6000, "rpn_post_nms_top_n": 300,
                "threshold": 0.7, "rpn_min_size": 16,
                "scales": (4.0, 8.0, 16.0, 32.0), "ratios": (0.5, 1.0, 2.0),
                "feature_stride": 16, "output_score": False,
                "iou_loss": False},
         num_outputs=lambda a: 2 if a.get("output_score") else 1,
         aliases=("Proposal", "_contrib_MultiProposal", "MultiProposal"))

# ------------------------------------------------------------------ krprod


def _khatri_rao(a, *mats):
    """Column-wise Khatri-Rao product (contrib/krprod.cc): row-wise in MXNet
    convention — inputs (r, n_i), output (r, prod n_i)."""
    out = mats[0]
    for m in mats[1:]:
        r = out.shape[0]
        out = (out[:, :, None] * m[:, None, :]).reshape(r, -1)
    return out


register("khatri_rao", _khatri_rao, variadic="num_args",
         attrs={"num_args": Required(int)},
         aliases=("_contrib_krprod", "_khatri_rao"))
