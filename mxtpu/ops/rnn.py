"""Fused RNN operator lowered to an XLA while-loop (lax.scan).

TPU-native replacement for the reference's RNN op (src/operator/rnn-inl.h:124),
which on GPU wraps cuDNN (src/operator/cudnn_rnn-inl.h) and on CPU is
unimplemented (src/operator/rnn.cc:32 LOG(FATAL)). Here there is ONE
implementation for all backends: per-timestep cell math expressed over jax
arrays, scanned over the sequence axis with ``lax.scan`` so XLA compiles it
into a single fused while loop whose body is MXU matmuls. Layers (and the two
directions of a bidirectional net) are unrolled in Python — ``num_layers`` is
a static attribute — so each layer's weights stay as separate large matmuls
that tile well onto the MXU.

Weight layout (our own, documented — the reference inherits cuDNN's opaque
filter blob): the ``parameters`` input is a flat vector packed as, for each
layer ``l`` in 0..num_layers-1, for each direction ``d`` (forward, then
backward when bidirectional):

    Wx[l,d]  shape (G*H, I_l)   input->hidden weight
    Wh[l,d]  shape (G*H, H)     hidden->hidden weight
    bx[l,d]  shape (G*H,)       input bias
    bh[l,d]  shape (G*H,)       hidden bias

concatenated flat in that order, where ``H = state_size``, ``I_0`` is the
input feature size, ``I_l = H * num_directions`` for l > 0, and G is the gate
count (1 for rnn_relu/rnn_tanh, 4 for lstm in gate order i,f,g,o, 3 for gru
in gate order r,z,n). ``rnn_pack_weights`` / ``rnn_unpack_weights`` convert
between this blob and per-gate dicts (parity with FusedRNNCell.unpack_weights,
python/mxnet/rnn/rnn_cell.py:620).

Data layout matches the reference: data is (seq_len, batch, feature) ("TNC"),
states are (num_layers*num_directions, batch, state_size).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from ..base import MXNetError
from .registry import Required, register

__all__ = ["rnn_param_size", "rnn_infer_input_size",
           "rnn_pack_weights", "rnn_unpack_weights",
           "GATE_COUNT", "GATE_NAMES"]

GATE_COUNT = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}
GATE_NAMES = {"rnn_relu": [""], "rnn_tanh": [""],
              "lstm": ["i", "f", "c", "o"], "gru": ["r", "z", "o"]}


def _layer_input_size(layer, input_size, state_size, num_directions):
    return input_size if layer == 0 else state_size * num_directions


def _layer_sizes(mode, layer, input_size, state_size, num_directions):
    """(Wx, Wh, bx, bh) element counts for one (layer, direction)."""
    gates = GATE_COUNT[mode]
    i = _layer_input_size(layer, input_size, state_size, num_directions)
    h = state_size
    return gates * h * i, gates * h * h, gates * h, gates * h


def rnn_param_size(num_layers, input_size, state_size, mode,
                   bidirectional=False):
    """Total element count of the flat ``parameters`` vector."""
    d = 2 if bidirectional else 1
    total = 0
    for l in range(num_layers):
        total += d * sum(_layer_sizes(mode, l, input_size, state_size, d))
    return total


def rnn_infer_input_size(flat_size, num_layers, state_size, mode,
                         bidirectional=False):
    """Inverse of rnn_param_size in the input dimension: recover the
    layer-0 input size from a flat ``parameters`` vector's length. The
    single source of truth for this arithmetic — FusedRNNCell's weight
    unpacking and the FusedRNN initializer both resolve geometry here."""
    d = 2 if bidirectional else 1
    g = GATE_COUNT[mode]
    h = state_size
    return int(flat_size // d // h // g) - \
        (num_layers - 1) * (h + d * h + 2) - h - 2


def _unpack(params, num_layers, input_size, state_size, mode, num_directions):
    """flat vector -> nested [layer][direction] dict of (Wx, Wh, bx, bh)."""
    gates = GATE_COUNT[mode]
    h = state_size
    out = []
    off = 0
    for l in range(num_layers):
        i = _layer_input_size(l, input_size, state_size, num_directions)
        per_dir = []
        for _d in range(num_directions):
            nwx, nwh, nbx, nbh = _layer_sizes(mode, l, input_size, h,
                                              num_directions)
            wx = params[off:off + nwx].reshape(gates * h, i); off += nwx
            wh = params[off:off + nwh].reshape(gates * h, h); off += nwh
            bx = params[off:off + nbx]; off += nbx
            bh = params[off:off + nbh]; off += nbh
            per_dir.append((wx, wh, bx, bh))
        out.append(per_dir)
    return out


def rnn_unpack_weights(params, num_layers, input_size, state_size, mode,
                       bidirectional=False):
    """Flat blob -> {name: array} with FusedRNNCell-style names like
    'l0_i2h_i_weight' / 'r0_h2h_f_bias' (l=forward, r=backward direction)."""
    d = 2 if bidirectional else 1
    layers = _unpack(_np.asarray(params), num_layers, input_size, state_size,
                     mode, d)
    gates, h = GATE_COUNT[mode], state_size
    names = GATE_NAMES[mode]
    out = {}
    for l, per_dir in enumerate(layers):
        for di, (wx, wh, bx, bh) in enumerate(per_dir):
            p = ("l%d" if di == 0 else "r%d") % l
            for g in range(gates):
                suf = ("_%s" % names[g]) if names[g] else ""
                out["%s_i2h%s_weight" % (p, suf)] = wx[g * h:(g + 1) * h]
                out["%s_h2h%s_weight" % (p, suf)] = wh[g * h:(g + 1) * h]
                out["%s_i2h%s_bias" % (p, suf)] = bx[g * h:(g + 1) * h]
                out["%s_h2h%s_bias" % (p, suf)] = bh[g * h:(g + 1) * h]
    return out


def rnn_pack_weights(weights, num_layers, input_size, state_size, mode,
                     bidirectional=False, dtype="float32"):
    """Inverse of rnn_unpack_weights: {name: array} -> flat blob."""
    d = 2 if bidirectional else 1
    gates, h = GATE_COUNT[mode], state_size
    names = GATE_NAMES[mode]
    parts = []
    for l in range(num_layers):
        for di in range(d):
            p = ("l%d" if di == 0 else "r%d") % l
            for kind in ("i2h_weight", "h2h_weight", "i2h_bias", "h2h_bias"):
                rows = []
                for g in range(gates):
                    suf = ("_%s" % names[g]) if names[g] else ""
                    key = "%s_%s%s_%s" % (p, kind.split("_")[0], suf,
                                          kind.split("_")[1])
                    rows.append(_np.asarray(weights[key], dtype=dtype))
                parts.append(_np.concatenate([r.reshape(-1) for r in rows]))
    return _np.concatenate(parts)


def _cell_step(mode, wx, wh, bx, bh, h_size, clip=None):
    """Return f(x_t, state) -> (new_state, output) for one direction/layer."""
    if mode == "lstm":
        def step(carry, x):
            h, c = carry
            gates = x @ wx.T + bx + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            if clip is not None:
                c2 = jnp.clip(c2, clip[0], clip[1])
            h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
            return (h2, c2), h2
    elif mode == "gru":
        def step(carry, x):
            h = carry
            xg = x @ wx.T + bx
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h2 = (1 - z) * n + z * h
            return h2, h2
    else:
        act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

        def step(carry, x):
            h = carry
            h2 = act(x @ wx.T + bx + h @ wh.T + bh)
            return h2, h2
    return step


def _run_direction(mode, x, h0, c0, wx, wh, bx, bh, reverse, clip=None):
    """Scan one direction over time. x: (T,N,I). Returns (out (T,N,H), hT, cT)."""
    step = _cell_step(mode, wx, wh, bx, bh, h0.shape[-1], clip=clip)
    carry0 = (h0, c0) if mode == "lstm" else h0
    # reverse=True scans t=T-1..0 but stacks outputs aligned with input
    # order (out[t] is the state after consuming x[T-1..t]).
    carry, out = lax.scan(step, carry0, x, reverse=reverse)
    if mode == "lstm":
        hT, cT = carry
    else:
        hT, cT = carry, None
    return out, hT, cT


def _rnn(a, rng, data, parameters, state, state_cell=None):
    mode = a.mode
    if mode not in GATE_COUNT:
        raise MXNetError("RNN: unknown mode '%s'" % mode)
    num_layers = int(a.num_layers)
    h_size = int(a.state_size)
    d = 2 if a.bidirectional else 1
    T, N, input_size = data.shape
    dt = data.dtype
    layers = _unpack(parameters.astype(dt), num_layers, input_size, h_size,
                     mode, d)
    p = float(a.p)
    training = bool(a.get("__is_train__", False))

    # batch-1 initial states broadcast up front: lax.scan carries must keep
    # a fixed shape, so the broadcast cannot happen inside the loop body
    if state.shape[1] != N:
        state = jnp.broadcast_to(state, (state.shape[0], N, h_size))
    if state_cell is not None and state_cell.shape[1] != N:
        state_cell = jnp.broadcast_to(state_cell,
                                      (state_cell.shape[0], N, h_size))
    x = data
    h_outs, c_outs = [], []
    for l in range(num_layers):
        if l > 0 and p > 0 and training:
            rng, sub = jax.random.split(rng)
            keep = jax.random.bernoulli(sub, 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), 0).astype(dt)
        dir_outs = []
        for di in range(d):
            wx, wh, bx, bh = [w.astype(dt) for w in layers[l][di]]
            h0 = state[l * d + di]
            c0 = state_cell[l * d + di] if mode == "lstm" else None
            clip = None
            if (mode == "lstm" and a.get("lstm_state_clip_min") is not None
                    and a.get("lstm_state_clip_max") is not None):
                clip = (float(a.lstm_state_clip_min),
                        float(a.lstm_state_clip_max))
            out, hT, cT = _run_direction(mode, x, h0, c0, wx, wh, bx, bh,
                                         reverse=(di == 1), clip=clip)
            dir_outs.append(out)
            h_outs.append(hT)
            if mode == "lstm":
                c_outs.append(cT)
        x = dir_outs[0] if d == 1 else jnp.concatenate(dir_outs, axis=-1)

    outputs = [x]
    if a.state_outputs:
        outputs.append(jnp.stack(h_outs, axis=0))
        if mode == "lstm":
            outputs.append(jnp.stack(c_outs, axis=0))
    return tuple(outputs)


def _rnn_args(a):
    base = ["data", "parameters", "state"]
    if a.get("mode") == "lstm":
        base.append("state_cell")
    return base


def _rnn_nout(a):
    if not a.get("state_outputs"):
        return 1
    return 3 if a.get("mode") == "lstm" else 2


def _rnn_infer(a, shapes):
    """Fill parameters/state shapes from the data shape (the reference's
    bidirectional InferShape; rnn-inl.h ListArguments)."""
    data = shapes[0]
    if data is None:
        return shapes
    T, N, input_size = data
    h = int(a.state_size)
    d = 2 if a.bidirectional else 1
    L = int(a.num_layers)
    psize = rnn_param_size(L, input_size, h, a.mode, a.bidirectional)
    out = [data, (psize,), (L * d, N, h)]
    if a.mode == "lstm":
        out.append((L * d, N, h))
    return out


register("RNN", _rnn, arg_names=_rnn_args,
         attrs={"state_size": Required(int), "num_layers": Required(int),
                "bidirectional": False, "mode": Required(str), "p": 0.0,
                "state_outputs": False, "lstm_state_clip_min": None,
                "lstm_state_clip_max": None, "__is_train__": False},
         num_outputs=_rnn_nout, needs_rng=True, infer_args=_rnn_infer,
         doc=_rnn.__doc__ or "Fused recurrent layer (lax.scan; TNC layout).")
