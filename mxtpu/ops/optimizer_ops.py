"""Fused optimizer update ops.

Parity: src/operator/optimizer_op.cc:37-278 (sgd_update, sgd_mom_update,
mp_sgd_update, mp_sgd_mom_update, adam_update, rmsprop_update, rmspropalex_update,
ftrl_update). Each is a single fused XLA computation; the Python Optimizer
dispatches here exactly like the reference's python/mxnet/optimizer.py does to its
fused kernels. Called with out= aliasing the weight so the wrapper mutates in
place (kWriteInplace semantics via functional update)."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import Required, register

_COMMON = {"lr": Required(float), "wd": 0.0, "rescale_grad": 1.0,
           "clip_gradient": -1.0}


def _prep(a, grad, weight):
    g = grad * a.rescale_grad
    if a.clip_gradient and a.clip_gradient > 0:
        g = jnp.clip(g, -a.clip_gradient, a.clip_gradient)
    return g + a.wd * weight


def _sgd_update(a, weight, grad):
    return weight - a.lr * _prep(a, grad, weight)


register("sgd_update", _sgd_update, arg_names=["weight", "grad"],
         attrs=dict(_COMMON))


def _sgd_mom_update(a, weight, grad, mom):
    g = _prep(a, grad, weight)
    new_mom = a.momentum * mom - a.lr * g
    return weight + new_mom, new_mom


register("sgd_mom_update", _sgd_mom_update, arg_names=["weight", "grad", "mom"],
         attrs=dict(_COMMON, momentum=0.0), num_outputs=2)


def _mp_sgd_update(a, weight, grad, weight32):
    g32 = _prep(a, grad.astype(jnp.float32), weight32)
    new_w32 = weight32 - a.lr * g32
    return new_w32.astype(weight.dtype), new_w32


register("mp_sgd_update", _mp_sgd_update, arg_names=["weight", "grad", "weight32"],
         attrs=dict(_COMMON), num_outputs=2)


def _mp_sgd_mom_update(a, weight, grad, mom, weight32):
    g32 = _prep(a, grad.astype(jnp.float32), weight32)
    new_mom = a.momentum * mom - a.lr * g32
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


register("mp_sgd_mom_update", _mp_sgd_mom_update,
         arg_names=["weight", "grad", "mom", "weight32"],
         attrs=dict(_COMMON, momentum=0.0), num_outputs=3)


def _adam_update(a, weight, grad, mean, var):
    g = grad * a.rescale_grad
    if a.clip_gradient and a.clip_gradient > 0:
        g = jnp.clip(g, -a.clip_gradient, a.clip_gradient)
    g = g + a.wd * weight
    new_mean = a.beta1 * mean + (1 - a.beta1) * g
    new_var = a.beta2 * var + (1 - a.beta2) * jnp.square(g)
    new_w = weight - a.lr * new_mean / (jnp.sqrt(new_var) + a.epsilon)
    return new_w, new_mean, new_var


register("adam_update", _adam_update, arg_names=["weight", "grad", "mean", "var"],
         attrs=dict(_COMMON, beta1=0.9, beta2=0.999, epsilon=1e-8), num_outputs=3)


def _rmsprop_update(a, weight, grad, n):
    g = _prep(a, grad, weight)
    new_n = (1 - a.gamma1) * jnp.square(g) + a.gamma1 * n
    new_w = weight - a.lr * g / jnp.sqrt(new_n + a.epsilon)
    if a.clip_weights and a.clip_weights > 0:
        new_w = jnp.clip(new_w, -a.clip_weights, a.clip_weights)
    return new_w, new_n


register("rmsprop_update", _rmsprop_update, arg_names=["weight", "grad", "n"],
         attrs=dict(_COMMON, gamma1=0.95, epsilon=1e-8, clip_weights=-1.0),
         num_outputs=2)


def _rmspropalex_update(a, weight, grad, n, g_avg, delta):
    g = _prep(a, grad, weight)
    new_n = (1 - a.gamma1) * jnp.square(g) + a.gamma1 * n
    new_g = (1 - a.gamma1) * g + a.gamma1 * g_avg
    new_delta = a.gamma2 * delta - a.lr * g / jnp.sqrt(new_n - jnp.square(new_g) + a.epsilon)
    new_w = weight + new_delta
    if a.clip_weights and a.clip_weights > 0:
        new_w = jnp.clip(new_w, -a.clip_weights, a.clip_weights)
    return new_w, new_n, new_g, new_delta


register("rmspropalex_update", _rmspropalex_update,
         arg_names=["weight", "grad", "n", "g", "delta"],
         attrs=dict(_COMMON, gamma1=0.95, gamma2=0.9, epsilon=1e-8,
                    clip_weights=-1.0),
         num_outputs=4)


def _ftrl_update(a, weight, grad, z, n):
    g = grad * a.rescale_grad
    if a.clip_gradient and a.clip_gradient > 0:
        g = jnp.clip(g, -a.clip_gradient, a.clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / a.lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= a.lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * a.lamda1)
        / ((a.beta + jnp.sqrt(new_n)) / a.lr + a.wd))
    return new_w, new_z, new_n


register("ftrl_update", _ftrl_update, arg_names=["weight", "grad", "z", "n"],
         attrs=dict(_COMMON, lamda1=0.01, beta=1.0), num_outputs=3)
