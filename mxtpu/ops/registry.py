"""Operator registry: every op is a pure JAX function plus metadata.

TPU-native replacement for the reference's NNVM op registry + FCompute kernels
(SURVEY.md L5/L6; include/mxnet/op_attr_types.h:171-240, 128x NNVM_REGISTER_OP +
54x MXNET_REGISTER_OP_PROPERTY). Instead of per-device kernel templates, each op
registers ONE pure function over jax arrays; imperative invoke jit-compiles it
per (attrs, shapes) and the graph executor inlines it into a whole-graph XLA
program, so memory planning / fusion / scheduling are XLA's job rather than
hand-written passes (replaces src/executor/*_pass.cc and the threaded engine's
per-op dispatch for compute).

Shape/type inference comes for free from ``jax.eval_shape`` over the same impl
(replaces src/executor/infer_graph_attr_pass.cc).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError, parse_attr

__all__ = ["OpDef", "register", "get_op", "list_ops", "Required", "invoke", "AttrDict"]

_OPS = {}


class Required:
    """Marker for a required attribute; carries the prototype type."""

    def __init__(self, proto):
        self.proto = proto

    def __repr__(self):
        return "Required(%s)" % getattr(self.proto, "__name__", self.proto)


class AttrDict(dict):
    """Hashable, attribute-access dict of parsed op attributes."""

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k)

    def __hash__(self):
        return hash(tuple(sorted((k, _hashable(v)) for k, v in self.items())))


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    return v


class OpDef:
    """Metadata + impl for one operator.

    Parameters
    ----------
    name : canonical op name (MXNet-compatible, e.g. 'Convolution', 'elemwise_add')
    fn : callable(attrs, *inputs) -> jax array or tuple of arrays.
        Pure; traced under jit. If ``needs_rng``, signature is (attrs, rng, *inputs).
    arg_names : names of tensor inputs in order.
    attrs : dict of attr name -> default (or Required(type)).
    num_outputs : int or callable(attrs)->int.
    variadic : if set, name of the attr holding the input count ('num_args');
        tensor inputs are then arg0..argN.
    needs_rng : op consumes a PRNG key (random ops, Dropout).
    aliases : extra registered names.
    loss_like : output is a head-loss (backward ignores incoming grads -- the op's
        fn must use jax.custom_vjp to encode that, like SoftmaxOutput).
    """

    def __init__(self, name, fn, arg_names=("data",), attrs=None, num_outputs=1,
                 variadic=None, needs_rng=False, aliases=(), loss_like=False,
                 aux_names=(), mutate_inputs=(), infer_args=None, doc=None):
        self.name = name
        self.fn = fn
        self.arg_names = arg_names if callable(arg_names) else list(arg_names)
        self.attrs_spec = dict(attrs or {})
        self.num_outputs = num_outputs
        self.variadic = variadic
        self.needs_rng = needs_rng
        self.aliases = aliases
        self.loss_like = loss_like
        # aux_names: trailing tensor inputs that are auxiliary states (reference:
        # BatchNorm moving_mean/moving_var). fn returns num_outputs visible outputs
        # followed by len(aux_names) updated aux values; the invoker writes those
        # back (imperative mutates the aux NDArrays; executor updates aux_states).
        self.aux_names = list(aux_names)
        # infer_args(attrs, in_shapes_with_None) -> full input shape list; fills
        # parameter shapes top-down (the only place the reference's bidirectional
        # InferShape pass is semantically required: weights/bias/bn stats)
        self.infer_args = infer_args
        self.mutate_inputs = mutate_inputs  # indices of inputs updated in place via out=
        self.doc = doc or (fn.__doc__ or "")
        self._jit_cache = {}

    # ---- attrs ----
    def parse_attrs(self, kwargs):
        out = AttrDict()
        for k, default in self.attrs_spec.items():
            if k in kwargs and kwargs[k] is not None:
                proto = default.proto if isinstance(default, Required) else default
                out[k] = parse_attr(kwargs[k], proto if proto is not None else None)
            elif isinstance(default, Required):
                raise MXNetError("op %s: required attr '%s' missing" % (self.name, k))
            else:
                out[k] = default
        extra = set(kwargs) - set(self.attrs_spec) - {"name", "out", "ctx", "dtype_hint"}
        # silently ignore unknown attrs the reference accepts for fwd-compat
        return out

    def n_out(self, attrs):
        return self.num_outputs(attrs) if callable(self.num_outputs) else self.num_outputs

    def input_names(self, attrs=None, n=None):
        if self.variadic:
            count = n if n is not None else (attrs or {}).get(self.variadic, 0)
            return ["arg%d" % i for i in range(count)]
        if callable(self.arg_names):
            return list(self.arg_names(attrs or AttrDict()))
        return self.arg_names

    # ---- compiled imperative execution ----
    def jitted(self, attrs):
        key = hash(attrs)
        f = self._jit_cache.get(key)
        if f is None:
            f = jax.jit(functools.partial(self.fn, attrs))
            self._jit_cache[key] = f
        return f

    def apply(self, attrs, inputs, rng=None):
        """Run the op eagerly (async via XLA dispatch). Returns tuple of arrays."""
        if self.needs_rng:
            out = self.jitted(attrs)(rng, *inputs)
        else:
            out = self.jitted(attrs)(*inputs)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return tuple(out)

    def trace(self, attrs, inputs, rng=None):
        """Run the op inside an outer trace (graph executor)."""
        if self.needs_rng:
            out = self.fn(attrs, rng, *inputs)
        else:
            out = self.fn(attrs, *inputs)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return tuple(out)

    def infer(self, attrs, in_avals):
        """Shape/dtype inference via jax.eval_shape (no FLOPs, no memory)."""
        structs = [jax.ShapeDtypeStruct(s, d) for s, d in in_avals]
        if self.needs_rng:
            rng = jax.ShapeDtypeStruct((2,), _np.uint32)
            out = jax.eval_shape(lambda r, *a: self.fn(attrs, r, *a), rng, *structs)
        else:
            out = jax.eval_shape(lambda *a: self.fn(attrs, *a), *structs)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return [(tuple(o.shape), o.dtype) for o in out]


def register(name, fn=None, **kwargs):
    """Register an op. Usable as decorator or direct call."""

    def _do(f):
        op = OpDef(name, f, **kwargs)
        _OPS[name] = op
        for a in op.aliases:
            _OPS[a] = op
        return f

    if fn is not None:
        _do(fn)
        return _OPS[name]
    return _do


def register_op(op):
    _OPS[op.name] = op
    for a in op.aliases:
        _OPS[a] = op
    return op


def get_op(name):
    if name not in _OPS:
        raise MXNetError("operator '%s' is not registered" % name)
    return _OPS[name]


def op_exists(name):
    return name in _OPS


def list_ops():
    return sorted(_OPS)


def invoke(name, inputs, attrs_kwargs, rng=None):
    """Imperative invoke on raw jax arrays: parse attrs, jit, run."""
    op = get_op(name)
    attrs = op.parse_attrs(attrs_kwargs)
    return op, attrs, op.apply(attrs, inputs, rng=rng)
