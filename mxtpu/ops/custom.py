"""The ``Custom`` operator: user Python code inside compiled graphs.

Reference: src/operator/custom/custom.cc (op registration :45-253, backward
:393) + python/mxnet/operator.py. Here the custom body runs as a
``jax.pure_callback`` (XLA host callback on TPU) and its gradient is wired
with ``jax.custom_vjp`` so it composes with both the autograd tape and
whole-graph executor tracing.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np

from .. import operator as _operator
from ..base import MXNetError
from .registry import AttrDict, OpDef, Required, register_op, register


class _CustomOpDef(OpDef):
    """OpDef that keeps ALL kwargs (custom ops take arbitrary str params)."""

    def parse_attrs(self, kwargs):
        if "op_type" not in kwargs:
            raise MXNetError("Custom op requires op_type=")
        out = AttrDict()
        for k, v in kwargs.items():
            if k in ("name", "out", "ctx", "dtype_hint"):
                continue
            out[k] = v if not isinstance(v, (list, dict)) else str(v)
        return out


def _prop_of(attrs):
    kwargs = {k: v for k, v in attrs.items() if k != "op_type"}
    return _operator.make_prop(attrs["op_type"], kwargs)


def _custom_fn(attrs, *inputs):
    prop = _prop_of(attrs)
    n_args = len(prop.list_arguments())
    n_out = len(prop.list_outputs())
    n_aux = len(prop.list_auxiliary_states())
    in_shapes = [tuple(x.shape) for x in inputs]
    arg_shapes, out_shapes, aux_shapes = prop.infer_shape(
        [list(s) for s in in_shapes[:n_args]])
    in_dt = [x.dtype for x in inputs]
    _, out_dtypes, _ = prop.infer_type(list(in_dt[:n_args]))
    out_structs = tuple(jax.ShapeDtypeStruct(tuple(s), d)
                        for s, d in zip(out_shapes, out_dtypes))
    from .. import autograd as _ag
    is_train = bool(_ag.is_training())

    def host_forward(*ins):
        op = prop.create_operator(None, [list(s) for s in in_shapes], in_dt)
        in_data = [_operator._HostArray(_np.asarray(x)) for x in ins]
        out_data = [_operator._HostArray(_np.zeros(s.shape, s.dtype))
                    for s in out_structs]
        aux = in_data[n_args:n_args + n_aux]
        op.forward(is_train, ["write"] * n_out, in_data[:n_args],
                   out_data, aux)
        return tuple(o.asnumpy().astype(s.dtype)
                     for o, s in zip(out_data, out_structs))

    def host_backward(ins, outs, cts):
        op = prop.create_operator(None, [list(s) for s in in_shapes], in_dt)
        in_data = [_operator._HostArray(_np.asarray(x)) for x in ins]
        out_data = [_operator._HostArray(_np.asarray(y)) for y in outs]
        out_grad = [_operator._HostArray(_np.asarray(c)) for c in cts]
        in_grad = [_operator._HostArray(_np.zeros_like(_np.asarray(x)))
                   for x in ins]
        aux = in_data[n_args:n_args + n_aux]
        op.backward(["write"] * len(ins), out_grad, in_data[:n_args],
                    out_data, in_grad, aux)
        return tuple(g.asnumpy().astype(d)
                     for g, d in zip(in_grad, in_dt))

    @jax.custom_vjp
    def run(*ins):
        return jax.pure_callback(host_forward, out_structs, *ins,
                                 vmap_method="sequential")

    def run_fwd(*ins):
        outs = jax.pure_callback(host_forward, out_structs, *ins,
                                 vmap_method="sequential")
        return outs, (ins, outs)

    def run_bwd(res, cts):
        ins, outs = res
        in_structs = tuple(jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
                           for x in ins)
        grads = jax.pure_callback(
            lambda *flat: host_backward(flat[:len(ins)],
                                        flat[len(ins):len(ins) + len(outs)],
                                        flat[len(ins) + len(outs):]),
            in_structs, *(tuple(ins) + tuple(outs) + tuple(cts)),
            vmap_method="sequential")
        return tuple(grads)

    run.defvjp(run_fwd, run_bwd)
    outs = run(*inputs)
    return outs if len(outs) > 1 else outs[0]


def _custom_arg_names(attrs):
    prop = _prop_of(attrs)
    return list(prop.list_arguments()) + list(prop.list_auxiliary_states())


def _custom_n_out(attrs):
    return len(_prop_of(attrs).list_outputs())


register_op(_CustomOpDef(
    "Custom", _custom_fn, arg_names=_custom_arg_names,
    attrs={"op_type": Required(str)}, num_outputs=_custom_n_out,
    aliases=("_Custom",)))


# ----------------------------------------------------------- _NoGradient


def _no_gradient(a):
    """Placeholder node meaning 'no gradient flows here' (reference
    elemwise_unary_op.cc _NoGradient): a constant zero scalar."""
    return jnp.zeros((1,), jnp.float32)


register("_NoGradient", _no_gradient, arg_names=[])
