"""The ``Custom`` operator: user Python code inside compiled graphs.

Reference: src/operator/custom/custom.cc (op registration :45-253, backward
:393) + python/mxnet/operator.py. Here the custom body runs as a
``jax.pure_callback`` (XLA host callback on TPU) and its gradient is wired
with ``jax.custom_vjp`` so it composes with both the autograd tape and
whole-graph executor tracing.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np

from .. import operator as _operator
from ..base import MXNetError
from .registry import AttrDict, OpDef, Required, register_op, register


class _CustomOpDef(OpDef):
    """OpDef that keeps ALL kwargs (custom ops take arbitrary str params)."""

    open_attrs = True  # JSON loader keeps every serialized attr

    def parse_attrs(self, kwargs):
        if "op_type" not in kwargs:
            raise MXNetError("Custom op requires op_type=")
        out = AttrDict()
        for k, v in kwargs.items():
            if k in ("name", "out", "ctx", "dtype_hint"):
                continue
            out[k] = v if not isinstance(v, (list, dict)) else str(v)
        return out


def _prop_of(attrs):
    kwargs = {k: v for k, v in attrs.items()
              if k not in ("op_type", "__is_train__")}
    return _operator.make_prop(attrs["op_type"], kwargs)


def _custom_fn(attrs, rng, *inputs):
    prop = _prop_of(attrs)
    # A uint32 seed derived from the op's traced PRNG key rides along as a
    # callback operand (and as a custom_vjp residual), so a stochastic
    # CustomOp body can draw the SAME randomness in every execution of
    # this step's forward — including the vjp's re-trace — and in its
    # backward. Exposed on the op instance as _mxtpu_rng_seed (used by the
    # torch bridge to keep dropout masks consistent across fwd/bwd).
    if rng is not None:
        seed_arr = jax.random.key_data(rng).reshape(-1)[-1].astype(
            jnp.uint32)
    else:
        seed_arr = jnp.uint32(0)
    n_args = len(prop.list_arguments())
    n_out = len(prop.list_outputs())
    n_aux = len(prop.list_auxiliary_states())
    in_shapes = [tuple(x.shape) for x in inputs]
    arg_shapes, out_shapes, aux_shapes = prop.infer_shape(
        [list(s) for s in in_shapes[:n_args]])
    in_dt = [x.dtype for x in inputs]
    _, out_dtypes, _ = prop.infer_type(list(in_dt[:n_args]))
    out_structs = tuple(jax.ShapeDtypeStruct(tuple(s), d)
                        for s, d in zip(out_shapes, out_dtypes))
    # Executor tracing injects __is_train__ (declared in attrs_spec below,
    # filled by _trace_graph); the imperative path has no executor, so the
    # autograd scope flag decides — without this, a Custom op inside a
    # bound executor always saw is_train=False (dropout-style CustomOps
    # silently ran in eval mode during training).
    from .. import autograd as _ag
    is_train = attrs.get("__is_train__")
    if is_train is None:
        is_train = bool(_ag.is_training())
    is_train = bool(is_train)

    def _make_op(seed):
        op = prop.create_operator(None, [list(s) for s in in_shapes], in_dt)
        op._mxtpu_rng_seed = int(_np.asarray(seed))
        return op

    def host_forward(seed, *ins):
        op = _make_op(seed)
        in_data = [_operator._HostArray(_np.asarray(x)) for x in ins]
        out_data = [_operator._HostArray(_np.zeros(s.shape, s.dtype))
                    for s in out_structs]
        aux = in_data[n_args:n_args + n_aux]
        op.forward(is_train, ["write"] * n_out, in_data[:n_args],
                   out_data, aux)
        return tuple(o.asnumpy().astype(s.dtype)
                     for o, s in zip(out_data, out_structs))

    def host_backward(seed, ins, outs, cts):
        op = _make_op(seed)
        in_data = [_operator._HostArray(_np.asarray(x)) for x in ins]
        out_data = [_operator._HostArray(_np.asarray(y)) for y in outs]
        out_grad = [_operator._HostArray(_np.asarray(c)) for c in cts]
        in_grad = [_operator._HostArray(_np.zeros_like(_np.asarray(x)))
                   for x in ins]
        aux = in_data[n_args:n_args + n_aux]
        op.backward(["write"] * len(ins), out_grad, in_data[:n_args],
                    out_data, in_grad, aux)
        return tuple(g.asnumpy().astype(d)
                     for g, d in zip(in_grad, in_dt))

    @jax.custom_vjp
    def run(*ins):
        return jax.pure_callback(host_forward, out_structs, seed_arr,
                                 *ins, vmap_method="sequential")

    def run_fwd(*ins):
        outs = jax.pure_callback(host_forward, out_structs, seed_arr,
                                 *ins, vmap_method="sequential")
        # seed rides in the residuals: run_bwd executes in a LATER trace
        # (cached vjp), so it must not close over this trace's seed tracer
        return outs, (seed_arr, ins, outs)

    def run_bwd(res, cts):
        seed, ins, outs = res
        in_structs = tuple(jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
                           for x in ins)
        grads = jax.pure_callback(
            lambda s, *flat: host_backward(
                s, flat[:len(ins)],
                flat[len(ins):len(ins) + len(outs)],
                flat[len(ins) + len(outs):]),
            in_structs, seed, *(tuple(ins) + tuple(outs) + tuple(cts)),
            vmap_method="sequential")
        return tuple(grads)

    run.defvjp(run_fwd, run_bwd)
    outs = run(*inputs)
    return outs if len(outs) > 1 else outs[0]


def _custom_arg_names(attrs):
    prop = _prop_of(attrs)
    return list(prop.list_arguments()) + list(prop.list_auxiliary_states())


def _custom_n_out(attrs):
    return len(_prop_of(attrs).list_outputs())


def _custom_infer_args(attrs, in_shapes):
    """Fill unknown input-Variable shapes from the prop's infer_shape —
    the reference's bidirectional InferShape lets a custom prop declare
    its parameter shapes (operator.py infer_shape returning corrected
    in_shapes); exceptions here fall back to leaving shapes unknown."""
    prop = _prop_of(attrs)
    n_args = len(prop.list_arguments())
    arg_shapes, _, aux_shapes = prop.infer_shape(
        [list(s) if s is not None else None for s in in_shapes[:n_args]])
    full = [tuple(s) if s is not None else None for s in arg_shapes]
    full += [tuple(s) for s in aux_shapes]
    return full + list(in_shapes[len(full):])


register_op(_CustomOpDef(
    "Custom", _custom_fn, arg_names=_custom_arg_names,
    attrs={"op_type": Required(str), "__is_train__": None},
    num_outputs=_custom_n_out, needs_rng=True,
    infer_args=_custom_infer_args, aliases=("_Custom",)))


# ----------------------------------------------------------- _NoGradient


def _no_gradient(a):
    """Placeholder node meaning 'no gradient flows here' (reference
    elemwise_unary_op.cc _NoGradient): a constant zero scalar."""
    return jnp.zeros((1,), jnp.float32)


register("_NoGradient", _no_gradient, arg_names=[])
