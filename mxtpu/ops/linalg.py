"""Linear-algebra ops (parity: src/operator/tensor/la_op.cc _linalg_* family,
backed by LAPACK via c_lapack_api.h in the reference; here by jnp.linalg/lax
which XLA lowers to MXU-friendly kernels)."""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _t(x, flag):
    return jnp.swapaxes(x, -1, -2) if flag else x


register("_linalg_gemm",
         lambda a, A, B, C: a.alpha * jnp.matmul(_t(A, a.transpose_a), _t(B, a.transpose_b)) + a.beta * C,
         arg_names=["A", "B", "C"],
         attrs={"transpose_a": False, "transpose_b": False, "alpha": 1.0, "beta": 1.0},
         aliases=("linalg_gemm",))
register("_linalg_gemm2",
         lambda a, A, B: a.alpha * jnp.matmul(_t(A, a.transpose_a), _t(B, a.transpose_b)),
         arg_names=["A", "B"],
         attrs={"transpose_a": False, "transpose_b": False, "alpha": 1.0},
         aliases=("linalg_gemm2",))
register("_linalg_potrf", lambda a, A: jnp.linalg.cholesky(A),
         arg_names=["A"], attrs={}, aliases=("linalg_potrf",))


def _potri(a, A):
    L = A
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    Linv = lax.linalg.triangular_solve(L, eye, left_side=True, lower=True)
    return jnp.matmul(jnp.swapaxes(Linv, -1, -2), Linv)


register("_linalg_potri", _potri, arg_names=["A"], attrs={},
         aliases=("linalg_potri",))
register("_linalg_trmm",
         lambda a, A, B: a.alpha * (jnp.matmul(_t(A, a.transpose), B) if not a.rightside
                                    else jnp.matmul(B, _t(A, a.transpose))),
         arg_names=["A", "B"],
         attrs={"transpose": False, "rightside": False, "alpha": 1.0},
         aliases=("linalg_trmm",))
register("_linalg_trsm",
         lambda a, A, B: a.alpha * lax.linalg.triangular_solve(
             A, B, left_side=not a.rightside, lower=True,
             transpose_a=bool(a.transpose)),
         arg_names=["A", "B"],
         attrs={"transpose": False, "rightside": False, "alpha": 1.0},
         aliases=("linalg_trsm",))
register("_linalg_sumlogdiag",
         lambda a, A: jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1),
         arg_names=["A"], attrs={}, aliases=("linalg_sumlogdiag",))
register("_linalg_syrk",
         lambda a, A: a.alpha * (jnp.matmul(A, jnp.swapaxes(A, -1, -2)) if not a.transpose
                                 else jnp.matmul(jnp.swapaxes(A, -1, -2), A)),
         arg_names=["A"], attrs={"transpose": False, "alpha": 1.0},
         aliases=("linalg_syrk",))


def _gelqf(a, A):
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(q, -1, -2), jnp.swapaxes(r, -1, -2)


register("_linalg_gelqf", _gelqf, arg_names=["A"], attrs={}, num_outputs=2,
         aliases=("linalg_gelqf",))
