"""Global PRNG state: a threefry key chain.

Parity: mx.random.seed (python/mxnet/random.py) + the per-device kRandom resource
(include/mxnet/resource.h:36-174). TPU-native: one splittable threefry key; every
imperative sampler consumes a fresh split so results are reproducible under
``seed`` regardless of async completion order.
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()


def _key():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
    return _state.key


def seed(seed_state):
    """Seed the global generator (parity mx.random.seed)."""
    _state.key = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Take a fresh subkey from the chain."""
    k = _key()
    _state.key, sub = jax.random.split(k)
    return sub


# imperative sampling front-ends (mx.random.uniform etc.) are generated onto
# mxtpu.ndarray and re-exported from mxtpu/__init__.py
