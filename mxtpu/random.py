"""Global PRNG state: a threefry key chain.

Parity: mx.random.seed (python/mxnet/random.py) + the per-device kRandom resource
(include/mxnet/resource.h:36-174). TPU-native: one splittable threefry key; every
imperative sampler consumes a fresh split so results are reproducible under
``seed`` regardless of async completion order.
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()


def _key():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
    return _state.key


def seed(seed_state):
    """Seed the global generator (parity mx.random.seed).

    Reference semantics: this does NOT touch numpy's global RNG.
    Host-side paths that draw from np.random (NDArrayIter shuffling, like
    the reference's python/mxnet/io.py) need np.random.seed alongside —
    the reference's own tests seed both."""
    _state.key = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Take a fresh subkey from the chain."""
    k = _key()
    _state.key, sub = jax.random.split(k)
    return sub


def get_state():
    """The current key-chain state as host numpy (elastic checkpointing:
    restoring it with :func:`set_state` makes every later ``next_key``
    reproduce the original chain exactly)."""
    import numpy as _np
    return _np.asarray(_key())


def set_state(key_data):
    """Restore a key chain captured by :func:`get_state` (accepts the raw
    uint32 key data as numpy/jax array)."""
    import jax.numpy as jnp
    import numpy as _np
    _state.key = jnp.asarray(_np.asarray(key_data, dtype=_np.uint32))


# imperative sampling front-ends (mx.random.uniform etc.) are generated onto
# mxtpu.ndarray and re-exported from mxtpu/__init__.py


# ------------------------------------------------- module-level samplers
# (parity: python/mxnet/random.py — mx.random.uniform/normal/... re-export
# the scalar-parameter sampling ops; NDArray-parameter variants live on
# nd.sample_*). Thin delegation to the generated nd.* sampler front-ends
# (one shared attr-plumbing path), plus explicit ctx placement, which the
# zero-input invoke path cannot infer. Late imports: ndarray imports this
# module at startup.

def _placed(arr, ctx):
    if ctx is None:
        return arr
    import jax

    from .ndarray import NDArray
    return NDArray(jax.device_put(arr._data, ctx.jax_device), ctx)


def uniform(low=0.0, high=1.0, shape=(1,), dtype="float32", ctx=None):
    from . import ndarray as nd
    return _placed(nd.uniform(low=float(low), high=float(high),
                              shape=shape, dtype=dtype), ctx)


def normal(loc=0.0, scale=1.0, shape=(1,), dtype="float32", ctx=None):
    from . import ndarray as nd
    return _placed(nd.random_normal(loc=float(loc), scale=float(scale),
                                    shape=shape, dtype=dtype), ctx)


def gamma(alpha=1.0, beta=1.0, shape=(1,), dtype="float32", ctx=None):
    from . import ndarray as nd
    return _placed(nd.random_gamma(alpha=float(alpha), beta=float(beta),
                                   shape=shape, dtype=dtype), ctx)


def exponential(lam=1.0, shape=(1,), dtype="float32", ctx=None):
    from . import ndarray as nd
    return _placed(nd.random_exponential(lam=float(lam), shape=shape,
                                         dtype=dtype), ctx)


def poisson(lam=1.0, shape=(1,), dtype="float32", ctx=None):
    from . import ndarray as nd
    return _placed(nd.random_poisson(lam=float(lam), shape=shape,
                                     dtype=dtype), ctx)


def negative_binomial(k=1, p=1.0, shape=(1,), dtype="float32", ctx=None):
    from . import ndarray as nd
    return _placed(nd.random_negative_binomial(k=int(k), p=float(p),
                                               shape=shape, dtype=dtype),
                   ctx)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(1,),
                                  dtype="float32", ctx=None):
    from . import ndarray as nd
    return _placed(nd.random_generalized_negative_binomial(
        mu=float(mu), alpha=float(alpha), shape=shape, dtype=dtype), ctx)


def multinomial(data, shape=(), get_prob=False, dtype="int32"):
    # default shape=() matches the reference sampler: one draw per prob
    # row, NO spurious trailing dim (sample_multinomial_op.h)
    from . import ndarray as nd
    return nd.sample_multinomial(data, shape=shape, get_prob=get_prob,
                                 dtype=dtype)
